"""Shared ResNet-50 benchmark core: the exact step, measurement protocol,
and MFU accounting used by ``bench.py`` — importable so the same number can
be produced INSIDE a ``tony submit`` job (BASELINE.md measures the north
star "via tony-submit", not via a bare script; see
``examples/resnet_bench_job``).

Protocol (ROOFLINE.md): the timed window is ONE jitted ``lax.scan`` over
``steps`` train steps (per-step dispatch over the remote PJRT relay costs
~5 ms); each window is fenced by device→host readback of the loss AND a
param leaf (``block_until_ready`` returns early through the relay); best
window of N wins (relay jitter is heavy-tailed).
"""

from __future__ import annotations

import functools
import os
import time

import jax
import jax.numpy as jnp

# Peak bf16 matmul FLOP/s per chip by generation (public spec sheets).
PEAK_BF16 = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def chip_generation() -> str:
    gen = os.environ.get("PALLAS_AXON_TPU_GEN") or os.environ.get(
        "TPU_ACCELERATOR_TYPE", "v5e")
    return gen.split("-")[0].lower()


def best_window_time(window, carry, params_of, default_windows=4):
    """Run ``window(carry) -> (carry, loss)`` twice as warmup (compile +
    steady state), then best-of-N timed runs, each device→host fenced.
    Returns ``(best_seconds, carry, loss)``."""
    carry, loss = window(carry)
    float(loss)
    carry, loss = window(carry)
    float(loss)
    best = float("inf")
    for _ in range(int(os.environ.get("BENCH_WINDOWS",
                                      str(default_windows)))):
        t0 = time.perf_counter()
        carry, loss = window(carry)
        float(loss)
        float(jax.tree_util.tree_leaves(params_of(carry))[0].ravel()[0])
        best = min(best, time.perf_counter() - t0)
    return best, carry, loss


def resnet_window(batch: int, image: int, steps: int, *,
                  s2d: bool = True, fused_bn: bool = False):
    """(window, carry): the full ResNet-50 train step (fwd + bwd + SGD +
    BatchNorm stats) on synthetic ImageNet-shaped bf16 data, scanned
    ``steps`` times per dispatch."""
    import optax

    from tony_tpu import train as tr
    from tony_tpu.models import get_model

    model = get_model("resnet50", fused_bn=fused_bn, s2d_stem=s2d)
    kx, ky, kinit = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(kx, (batch, image, image, 3), jnp.bfloat16)
    y = jax.random.randint(ky, (batch,), 0, 1000)
    variables = jax.jit(lambda: model.init(kinit, x, train=False))()
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = jax.jit(tx.init)(params)

    def step(carry, _):
        params, opt_state, batch_stats = carry

        def loss_fn(p):
            logits, updates = model.apply(
                {"params": p, "batch_stats": batch_stats}, x, train=True,
                mutable=["batch_stats"])
            return tr.cross_entropy_loss(logits, y), updates["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state, new_stats), loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def window(carry):
        carry, losses = jax.lax.scan(step, carry, None, length=steps)
        return carry, losses[-1]

    return window, (params, opt_state, batch_stats)


def fsdp_shard_state(state, mesh):
    """Re-create a TrainState with params (and fresh optimizer state) in
    the ZeRO-3 layout: each param's first fsdp-divisible dim is sharded
    over the fsdp axis, the rest stay replicated — the manual analogue of
    what ``create_train_state`` produces for models carrying "embed"
    logical axes."""
    from flax.training.train_state import TrainState
    from jax.sharding import NamedSharding, PartitionSpec as P

    F = mesh.shape["fsdp"]

    def spec_of(p):
        for d, n in enumerate(p.shape):
            if n % F == 0:
                return P(*([None] * d + ["fsdp"]
                           + [None] * (p.ndim - d - 1)))
        return P()

    shardings = jax.tree.map(
        lambda p: NamedSharding(mesh, spec_of(p)), state.params)
    params = jax.device_put(state.params, shardings)
    from tony_tpu.ops import fused_optim

    if isinstance(state.tx, fused_optim.FusedOptimizer):
        # Bucket-resident state is planned off committed shardings, so it
        # must be rebuilt AFTER the reshard, not GSPMD-propagated.
        return TrainState(step=0, apply_fn=state.apply_fn, params=params,
                          tx=state.tx,
                          opt_state=state.tx.init_state(params, mesh))
    return TrainState.create(apply_fn=state.apply_fn, params=params,
                             tx=state.tx)


def run_overlap_bench(*, batch: int | None = None, hidden: int = 512,
                      steps: int | None = None, microbatches: int = 4,
                      bucket_bytes: int = 1 << 20,
                      reduce_op: str = "all_reduce",
                      slices: int = 1, fsdp: int = 1,
                      zero3: bool = False, hierarchy: str = "auto",
                      on_tpu: bool | None = None) -> dict:
    """Overlap-engine leg: monolithic GSPMD step vs bucketed-accumulation
    step (``make_accum_train_step``) on a DP mesh over all local devices,
    same model / optimizer / data.

    ``slices=2`` builds a (host-simulated) multi-slice mesh and exercises
    the hierarchical ICI/DCN reduce; ``zero3=True`` (with ``fsdp>1``)
    shards the params so the accum step runs the psum_scatter-into-shard
    path. Reports both step times, the speedup, the bucket plan (count and
    per-bucket bytes — the numbers the latency-hiding scheduler pipelines,
    plus the per-level plan for hierarchical/ZeRO-3 runs), and the
    numerics deltas between the two paths: the bucketed step must match
    the monolithic step's loss and grad-norm within 1e-5 or the comparison
    is void (``numerics_ok`` gates the headline).
    """
    import optax

    from tony_tpu import parallel as par
    from tony_tpu import profiler
    from tony_tpu import train as tr
    from tony_tpu.models import get_model
    from tony_tpu.parallel import overlap

    if on_tpu is None:
        on_tpu = jax.default_backend() not in ("cpu",)
    if steps is None:
        steps = 20 if on_tpu else 4
    mesh = par.make_mesh(slices=slices, fsdp=fsdp)   # rest of devices: data
    dp = overlap.sync_size(mesh)
    if batch is None:
        batch = dp * microbatches * (16 if on_tpu else 4)
    model = get_model("mnist-mlp", hidden=hidden)
    kx, ky, kr = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(kx, (batch, 784), jnp.float32)
    y = jax.random.randint(ky, (batch,), 0, 10)
    data = {"x": x, "y": y}
    state = tr.create_train_state(model, optax.sgd(0.1, momentum=0.9),
                                  x, kr)
    if zero3:
        if fsdp <= 1:
            raise ValueError("zero3=True needs fsdp > 1")
        state = fsdp_shard_state(state, mesh)
        specs = overlap.fsdp_param_specs(state.params, mesh)
        plan = overlap.GradBuckets.plan_sharded(
            state.params, specs, shard_size=fsdp, bucket_bytes=bucket_bytes)
    else:
        plan = overlap.GradBuckets.plan(state.params, bucket_bytes)

    profiler.reset_overlap_records()
    mono = tr.make_train_step(mesh=mesh, donate=False)
    accum = tr.make_accum_train_step(
        mesh=mesh, microbatches=microbatches, bucket_bytes=bucket_bytes,
        reduce_op=reduce_op, hierarchy=hierarchy, donate=False)
    # Numerics pin first, from the identical initial state.
    _, m_mono = mono(state, data)
    _, m_accum = accum(state, data)
    loss_delta = abs(float(m_mono["loss"]) - float(m_accum["loss"]))
    gnorm_delta = abs(float(m_mono["grad_norm"])
                      - float(m_accum["grad_norm"]))

    def timed(step_fn):
        def window(st):
            metrics = None
            for _ in range(steps):
                st, metrics = step_fn(st, data)
            return st, metrics["loss"]
        best, _, _ = best_window_time(window, state,
                                      params_of=lambda s: s.params)
        return best / steps

    mono_s = timed(mono)
    accum_s = timed(accum)
    records = profiler.overlap_report()
    return {
        "metric": "overlap_bench",
        "mono_step_s": round(mono_s, 6),
        "accum_step_s": round(accum_s, 6),
        "speedup": round(mono_s / accum_s, 4) if accum_s else None,
        "microbatches": microbatches,
        "reduce_op": reduce_op,
        "slices": slices,
        "fsdp": fsdp,
        "zero3": zero3,
        "hierarchy": records.get("accum_step", {}).get("hierarchy",
                                                       hierarchy),
        "n_buckets": plan.n_buckets,
        "n_scatter_buckets": plan.n_scatter_buckets,
        "bucket_nbytes": list(plan.bucket_nbytes),
        "bucket_threshold": plan.threshold,
        "loss_delta": loss_delta,
        "grad_norm_delta": gnorm_delta,
        "numerics_ok": bool(loss_delta < 1e-5 and gnorm_delta < 1e-5),
        "overlap_records": records,
        "batch": batch,
        "dp": dp,
        "backend": jax.default_backend(),
    }


def run_overlap_sweep(bucket_bytes_list=(64 << 10, 256 << 10, 1 << 20,
                                         4 << 20),
                      **kw) -> dict:
    """Bucket-bytes sweep over :func:`run_overlap_bench` — the tuning
    curve for the planner threshold (ROADMAP: record in BENCH). Returns
    the per-threshold legs trimmed to the numbers that move."""
    legs = []
    for bb in bucket_bytes_list:
        r = run_overlap_bench(bucket_bytes=bb, **kw)
        legs.append({k: r[k] for k in (
            "bucket_threshold", "n_buckets", "n_scatter_buckets",
            "mono_step_s", "accum_step_s", "speedup", "numerics_ok")})
    return {
        "metric": "overlap_bucket_sweep",
        "slices": kw.get("slices", 1),
        "fsdp": kw.get("fsdp", 1),
        "zero3": kw.get("zero3", False),
        "backend": jax.default_backend(),
        "legs": legs,
    }


def run_sched_bench(*, leaves: int = 96, leaf_rows: int = 16,
                    leaf_cols: int = 64, fsdp: int | None = None,
                    bucket_bytes: int = 256 << 10, prefetch: int = 1,
                    microbatches: int = 4, a2a_chunks: int = 2,
                    steps: int | None = None,
                    on_tpu: bool | None = None) -> dict:
    """Collective-scheduler leg (tony_tpu.parallel.sched), three probes:

    1. **Forward gathers** — a ``leaves``-leaf fsdp-sharded param tree
       gathered per leaf (the pre-scheduler path) vs coalesced into
       shard-major byte-threshold buckets with prefetch chaining
       (:class:`~tony_tpu.parallel.sched.GatherPlan`). The gather-only
       step has nothing to hide under, so its wall time IS the exposed
       gather time; ``gather_2x_ok`` (bucketed ≥ 2× faster) gates the
       headline, and the gathered values are pinned bit-exact.
    2. **ZeRO-3 step numerics** — ``microbatch_grads`` with
       ``gather="bucketed"`` vs ``gather="per_leaf"`` on the same state:
       loss and every grad leaf must match BIT-exact (bucketing is pure
       data movement), plus both full accum-step times.
    3. **MoE a2a** — the GSPMD dispatch-einsum path vs the scheduler's
       explicit per-capacity-chunk ``all_to_all``
       (:func:`~tony_tpu.parallel.sched.moe_dispatch_ffn_combine`) on an
       ``ep`` mesh, output delta + step times. On the host-simulated mesh
       the a2a timing is directional; the numerics and the record schema
       are the CPU-verifiable part.

    The unified ``profiler.collective_report()`` snapshot rides along so
    the bench JSON shows every collective the step issued.
    """
    import flax.linen as nn
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tony_tpu import parallel as par
    from tony_tpu import profiler
    from tony_tpu import train as tr
    from tony_tpu.compat import shard_map
    from tony_tpu.models import get_model
    from tony_tpu.models.moe import MoEMLP
    from tony_tpu.parallel import overlap, sched

    if on_tpu is None:
        on_tpu = jax.default_backend() not in ("cpu",)
    if steps is None:
        steps = 20 if on_tpu else 8
    n_dev = len(jax.devices())
    if fsdp is None:
        fsdp = 4 if n_dev % 4 == 0 else (2 if n_dev % 2 == 0 else 1)
    windows = int(os.environ.get("BENCH_WINDOWS", "3"))
    profiler.reset_collective_records()

    # --- leg 1: per-leaf vs bucketed+prefetched forward gathers --------
    mesh = par.make_mesh(fsdp=fsdp)
    keys = jax.random.split(jax.random.PRNGKey(0), leaves)
    params = {f"w{i:03d}": jax.random.normal(
        keys[i], (leaf_rows, leaf_cols), jnp.float32)
        for i in range(leaves)}
    specs = jax.tree.map(lambda _: P("fsdp"), params)
    params = jax.device_put(params, jax.tree.map(
        lambda _: NamedSharding(mesh, P("fsdp")), params))
    plan = overlap.GradBuckets.plan_sharded(
        params, specs, shard_size=fsdp, bucket_bytes=bucket_bytes)
    gplan = sched.GatherPlan.from_buckets(plan, prefetch=prefetch)

    def consume(leaves_full):
        # Touch every gathered element so no gather can be elided.
        return sum(l.sum() for l in leaves_full)

    def per_leaf_fn(p):
        def spmd(p):
            return consume([jax.lax.all_gather(l, "fsdp", axis=0,
                                               tiled=True)
                            for l in jax.tree.leaves(p)])
        return shard_map(spmd, mesh, in_specs=(specs,),
                         out_specs=P())(p)

    def bucketed_fn(p):
        def spmd(p):
            return consume(gplan.gather(jax.tree.leaves(p)))
        return shard_map(spmd, mesh, in_specs=(specs,),
                         out_specs=P())(p)

    def timed(fn, arg, jit=True):
        # One timing methodology per file: the shared best-of-N fenced
        # window harness (warmup + loss AND param-leaf readback fences).
        # jit=False for callables that are already jitted inside (the
        # accum stepper: its layout detection reads committed shardings
        # off the REAL leaves and must not be traced).
        f = jax.jit(fn) if jit else fn

        def window(carry):
            out = None
            for _ in range(steps):
                out = f(carry)
            return carry, out

        def first_array(c):
            # Fence on a device leaf (TrainState.step is a plain int).
            return next(l for l in jax.tree_util.tree_leaves(c)
                        if hasattr(l, "ravel"))

        best, _, _ = best_window_time(window, arg, params_of=first_array,
                                      default_windows=windows)
        return best / steps

    per_leaf_s = timed(per_leaf_fn, params)
    bucketed_s = timed(bucketed_fn, params)

    # Bit-exact pin on the gathered VALUES (bucketing is data movement).
    def gathered_values(use_plan):
        def spmd(p):
            ls = jax.tree.leaves(p)
            if use_plan:
                return gplan.gather(ls)
            return [jax.lax.all_gather(l, "fsdp", axis=0, tiled=True)
                    for l in ls]
        return shard_map(spmd, mesh, in_specs=(specs,),
                         out_specs=[P()] * leaves)(params)

    gather_exact = all(
        np.array_equal(np.asarray(jax.device_get(a)),
                       np.asarray(jax.device_get(b)))
        for a, b in zip(gathered_values(True), gathered_values(False)))

    # --- leg 2: ZeRO-3 accum step, bucketed vs per-leaf gathers --------
    model = get_model("mnist-mlp", hidden=512)
    kx, ky, kr = jax.random.split(jax.random.PRNGKey(1), 3)
    dp = overlap.sync_size(mesh)
    batch_n = dp * microbatches * (16 if on_tpu else 4)
    x = jax.random.normal(kx, (batch_n, 784), jnp.float32)
    yb = jax.random.randint(ky, (batch_n,), 0, 10)
    data = {"x": x, "y": yb}
    state = fsdp_shard_state(
        tr.create_train_state(model, optax.sgd(0.1, momentum=0.9), x, kr),
        mesh)
    z_specs = overlap.fsdp_param_specs(state.params, mesh)

    def loss_fn(p, mb):
        logits = state.apply_fn({"params": p}, mb["x"])
        return tr.cross_entropy_loss(logits, mb["y"])

    grads_by_mode = {}
    for mode in ("bucketed", "per_leaf"):
        grads_by_mode[mode] = jax.jit(lambda p, b, m=mode: overlap.
                                      microbatch_grads(
                                          loss_fn, p, b, mesh,
                                          microbatches=microbatches,
                                          bucket_bytes=bucket_bytes,
                                          param_specs=z_specs, gather=m,
                                          prefetch=prefetch))(state.params,
                                                             data)
    (l_b, g_b), (l_p, g_p) = (grads_by_mode["bucketed"],
                              grads_by_mode["per_leaf"])
    zero3_exact = bool(float(l_b) == float(l_p)) and all(
        np.array_equal(np.asarray(jax.device_get(a)),
                       np.asarray(jax.device_get(b)))
        for a, b in zip(jax.tree.leaves(g_b), jax.tree.leaves(g_p)))

    step_s = {}
    for mode in ("bucketed", "per_leaf"):
        step_fn = tr.make_accum_train_step(
            mesh=mesh, microbatches=microbatches,
            bucket_bytes=bucket_bytes, gather=mode, prefetch=prefetch,
            donate=False)
        step_s[mode] = timed(
            lambda st, f=step_fn: f(st, data)[1]["loss"], state,
            jit=False)

    # --- leg 3: MoE a2a under the scheduler vs GSPMD default -----------
    moe = {}
    ep = 2 if n_dev % 2 == 0 else 1
    if ep > 1:
        mesh_e = par.make_mesh(ep=ep)
        b, t, d, f, e = (16 if on_tpu else 8), 16, 64, 128, 2 * ep
        xk = jax.random.normal(jax.random.PRNGKey(2), (b, t, d),
                               jnp.float32)
        layer = MoEMLP(dim=d, ffn_hidden=f, n_experts=e, top_k=2,
                       dtype=jnp.float32)
        variables = {"params": nn.unbox(
            layer.init(jax.random.PRNGKey(3), xk))["params"]}
        w_shard = {"params": {
            k: NamedSharding(mesh_e, P("expert"))
            if k.startswith("w_") and k != "w_router"
            else NamedSharding(mesh_e, P())
            for k in variables["params"]}}
        v_sh = jax.device_put(variables, w_shard)
        x_sh = jax.device_put(xk, par.batch_sharding(mesh_e))

        def gspmd_fn(v, xx):
            with nn.logical_axis_rules(par.RULES):
                return layer.apply(v, xx)

        layer_s = MoEMLP(dim=d, ffn_hidden=f, n_experts=e, top_k=2,
                         dtype=jnp.float32, explicit_a2a=True,
                         mesh=mesh_e, a2a_chunks=a2a_chunks)
        sched_fn = lambda v, xx: layer_s.apply(v, xx)
        y_g = jax.jit(gspmd_fn)(v_sh, x_sh)
        y_s = jax.jit(sched_fn)(v_sh, x_sh)
        moe = {
            "moe_gspmd_s": round(timed(lambda v: gspmd_fn(v, x_sh).sum(),
                                       v_sh), 6),
            "moe_sched_s": round(timed(lambda v: sched_fn(v, x_sh).sum(),
                                       v_sh), 6),
            "moe_a2a_chunks": a2a_chunks,
            "moe_delta": float(jnp.max(jnp.abs(
                jax.device_get(y_g) - jax.device_get(y_s)))),
        }
        moe["moe_numerics_ok"] = bool(moe["moe_delta"] < 1e-5)

    out = {
        "metric": "sched_bench",
        "gather_per_leaf_s": round(per_leaf_s, 6),
        "gather_bucketed_s": round(bucketed_s, 6),
        "gather_speedup": round(per_leaf_s / bucketed_s, 4)
        if bucketed_s else None,
        "gather_2x_ok": bool(bucketed_s and per_leaf_s >= 2 * bucketed_s),
        "gather_bitexact": bool(gather_exact),
        "n_leaves": leaves,
        "n_gather_buckets": gplan.n_gather_buckets,
        "gather_nbytes": list(gplan.gather_nbytes),
        "prefetch": prefetch,
        "zero3_step_bucketed_s": round(step_s["bucketed"], 6),
        "zero3_step_per_leaf_s": round(step_s["per_leaf"], 6),
        "zero3_bitexact": bool(zero3_exact),
        "fsdp": fsdp,
        "microbatches": microbatches,
        "bucket_threshold": bucket_bytes,
        "backend": jax.default_backend(),
        **moe,
        "collective_records": profiler.collective_report(),
    }
    return out


def _count_eqns(jaxpr) -> int:
    """Total jaxpr equation count, sub-jaxprs included — the dispatch-
    granularity proxy the optimizer legs report (per-leaf optax updates
    scale O(n_leaves), the fused plane O(n_buckets))."""
    import jax.core

    n = len(jaxpr.eqns)
    for eq in jaxpr.eqns:
        for v in eq.params.values():
            vs = v if isinstance(v, (list, tuple)) else (v,)
            for x in vs:
                inner = getattr(x, "jaxpr", x)
                if hasattr(inner, "eqns"):
                    n += _count_eqns(inner)
    return n


def run_optim_bench(*, leaves: int = 192, leaf_rows: int = 16,
                    leaf_cols: int = 64, fsdp: int | None = None,
                    bucket_bytes: int = 256 << 10, rule: str = "adamw",
                    steps: int | None = None,
                    on_tpu: bool | None = None) -> dict:
    """Fused-optimizer leg (tony_tpu.ops.fused_optim): per-leaf optax
    updates vs the bucket-major fused update on a ``leaves``-leaf
    fsdp-sharded tree (the many-small-leaves regime where the per-leaf op
    soup is dispatch-bound — every leaf costs its own multiply/add chain
    while the fused plane issues one update per bucket buffer).

    Three numbers gate the headline: wall time per update (both paths
    jitted, donated, fenced best-of-N), the jaxpr equation counts (the
    O(n_leaves) vs O(n_buckets) claim, compiler-visible), and the f32
    numerics pin (the fused params must match optax BIT-exact — the same
    pin ``tests/test_fused_optim.py`` holds; ``numerics_ok`` gates the
    timing claim like every other leg).
    """
    import numpy as np
    import optax

    from tony_tpu import parallel as par
    from tony_tpu import profiler
    from tony_tpu.ops import fused_optim
    from tony_tpu.parallel import overlap

    if on_tpu is None:
        on_tpu = jax.default_backend() not in ("cpu",)
    if steps is None:
        steps = 20 if on_tpu else 10
    n_dev = len(jax.devices())
    if fsdp is None:
        fsdp = 4 if n_dev % 4 == 0 else (2 if n_dev % 2 == 0 else 1)
    windows = int(os.environ.get("BENCH_WINDOWS", "3"))
    mesh = par.make_mesh(fsdp=fsdp)
    from jax.sharding import NamedSharding, PartitionSpec as P

    keys = jax.random.split(jax.random.PRNGKey(0), 2 * leaves)
    params = {f"w{i:03d}": jax.random.normal(
        keys[i], (leaf_rows, leaf_cols), jnp.float32)
        for i in range(leaves)}
    shardings = jax.tree.map(
        lambda _: NamedSharding(mesh, P("fsdp")), params)
    params = jax.device_put(params, shardings)
    grads = jax.device_put(
        {k: jax.random.normal(keys[leaves + i],
                              (leaf_rows, leaf_cols), jnp.float32) * 1e-2
         for i, k in enumerate(params)}, shardings)
    specs = overlap.fsdp_param_specs(params, mesh)

    fused = fused_optim.FusedOptimizer(
        rule=rule, lr=1e-3, weight_decay=1e-2, bucket_bytes=bucket_bytes)
    plan = fused.plan_for(params, mesh)
    profiler.reset_update_records()
    opt0 = fused.init_state(params, mesh, plan=plan)

    tx = optax.adamw(1e-3, weight_decay=1e-2) if rule == "adamw" \
        else optax.sgd(1e-3, momentum=0.9)
    # Leaf-major optax state in the params' layout (GSPMD-propagated, as
    # apply_gradients would hold it).
    oopt0 = jax.jit(tx.init)(params)

    def fused_fn(p, s):
        new_p, new_s, _ = fused_optim.fused_update_step(
            fused, p, grads, s, mesh, plan=plan, param_specs=specs)
        return new_p, new_s

    def optax_fn(p, s):
        u, s2 = tx.update(grads, s, p)
        return optax.apply_updates(p, u), s2

    fused_jit = jax.jit(fused_fn, donate_argnums=(0, 1))
    optax_jit = jax.jit(optax_fn, donate_argnums=(0, 1))

    # Numerics pin before the timed (donating) runs.
    fp, _ = jax.jit(fused_fn)(params, opt0)
    op, _ = jax.jit(optax_fn)(params, oopt0)
    exact = all(np.array_equal(np.asarray(jax.device_get(a)),
                               np.asarray(jax.device_get(b)))
                for a, b in zip(jax.tree.leaves(fp), jax.tree.leaves(op)))

    eqns = {
        "fused": _count_eqns(jax.make_jaxpr(fused_fn)(params, opt0).jaxpr),
        "optax": _count_eqns(jax.make_jaxpr(optax_fn)(params, oopt0).jaxpr),
    }

    def timed(step_jit, p, s):
        def window(carry):
            p, s = carry
            for _ in range(steps):
                p, s = step_jit(p, s)
            return (p, s), jax.tree.leaves(p)[0].ravel()[0]

        best, _, _ = best_window_time(
            window, (p, s),
            params_of=lambda c: jax.tree.leaves(c[0])[0],
            default_windows=windows)
        return best / steps

    # Fresh device trees per timed leg: the jitted steps donate their
    # inputs, so the originals are dead after the first call.
    host_p = jax.device_get(params)
    p_f = jax.device_put(host_p, shardings)
    fused_s = timed(fused_jit, p_f, fused.init_state(p_f, mesh, plan=plan))
    p_o = jax.device_put(host_p, shardings)
    optax_s = timed(optax_jit, p_o, jax.jit(tx.init)(p_o))
    return {
        "metric": "optim_bench",
        "rule": rule,
        "optax_update_s": round(optax_s, 6),
        "fused_update_s": round(fused_s, 6),
        "speedup": round(optax_s / fused_s, 4) if fused_s else None,
        "n_leaves": leaves,
        "n_buckets": plan.n_buckets,
        "n_scatter_buckets": plan.n_scatter_buckets,
        "bucket_nbytes": list(plan.bucket_nbytes),
        "bucket_threshold": bucket_bytes,
        "optax_jaxpr_eqns": eqns["optax"],
        "fused_jaxpr_eqns": eqns["fused"],
        "numerics_ok": bool(exact),
        "fsdp": fsdp,
        "update_records": profiler.update_report(),
        "backend": jax.default_backend(),
    }


def run_ckpt_bench(*, hidden: int = 2048, steps: int = 4, saves: int = 3,
                   fsdp: int = 1, directory: str | None = None) -> dict:
    """Checkpoint-plane leg: blocking save wall time vs the stall an async
    save actually charges the train loop (slot wait + device→host extract;
    the serialize/fsync/commit overlaps subsequent steps on the writer
    thread). Same state, same directory tree, best-of-``saves`` each.

    ``fsdp > 1`` shards the state first so the saves exercise the shard-
    local write path (each process writes only its replica-0 chunks).
    The restore leg re-reads the last committed step and pins it bit-exact
    against the live state — a save that stalls less but restores wrong
    is not a checkpoint. ``overlap_ok`` (async stall < blocking save)
    gates the headline, mirroring ``numerics_ok`` in the overlap bench.
    """
    import shutil
    import tempfile
    from pathlib import Path

    import numpy as np
    import optax

    from tony_tpu import ckpt as ckpt_mod
    from tony_tpu import parallel as par
    from tony_tpu import profiler
    from tony_tpu import train as tr
    from tony_tpu.models import get_model

    mesh = par.make_mesh(fsdp=fsdp)
    dp = 1
    for a in mesh.axis_names:
        dp *= mesh.shape[a]
    batch = dp * 4
    model = get_model("mnist-mlp", hidden=hidden)
    kx, ky, kr = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(kx, (batch, 784), jnp.float32)
    y = jax.random.randint(ky, (batch,), 0, 10)
    data = {"x": x, "y": y}
    state = tr.create_train_state(model, optax.sgd(0.1, momentum=0.9),
                                  x, kr)
    if fsdp > 1:
        state = fsdp_shard_state(state, mesh)
    step = tr.make_train_step(mesh=mesh, donate=False)
    state, _ = step(state, data)            # warm the compile
    root = Path(directory) if directory else Path(tempfile.mkdtemp(
        prefix="tony-ckpt-bench-"))
    profiler.reset_ckpt_records()
    try:
        blocking = ckpt_mod.AsyncCheckpointer(root / "blocking", keep=2)
        blocking_s = []
        for i in range(saves):
            t0 = time.perf_counter()
            blocking.save(state, step=i + 1, block=True)
            blocking_s.append(time.perf_counter() - t0)
        blocking.close()
        profiler.record_ckpt("blocking_save", save_s=min(blocking_s),
                             nbytes=blocking.stats["nbytes"])

        async_c = ckpt_mod.AsyncCheckpointer(root / "async", keep=2)
        overlap_step_s = []
        for i in range(saves):
            async_c.save(state, step=i + 1)      # stall recorded inside
            t0 = time.perf_counter()             # steps riding the write
            for _ in range(steps):
                state, _ = step(state, data)
            jax.block_until_ready(state.params)
            overlap_step_s.append((time.perf_counter() - t0) / steps)
        async_c.wait()
        stall_s = min(async_c.stats["stall_s"])
        write_s = min(async_c.stats["write_s"])
        nbytes = async_c.stats["nbytes"]

        # Restore pin: save the CURRENT state once more (the earlier async
        # saves snapshotted older states) and require the committed step
        # to round-trip bit-exact through the elastic path (mesh-mapped
        # specs, no target shardings) — a save that stalls less but
        # restores wrong is not a checkpoint.
        async_c.save(state, step=saves + 1, block=True)
        abstract = jax.tree.map(
            lambda a: np.zeros(a.shape, a.dtype)
            if hasattr(a, "shape") else a, jax.device_get(state))
        restored = ckpt_mod.restore_pytree(root / "async", abstract,
                                           mesh=mesh)
        exact = all(
            np.array_equal(np.asarray(jax.device_get(a)),
                           np.asarray(jax.device_get(b)))
            for a, b in zip(jax.tree.leaves(restored),
                            jax.tree.leaves(state))
            if hasattr(b, "shape"))
        async_c.close()
    finally:
        if not directory:
            shutil.rmtree(root, ignore_errors=True)
    return {
        "metric": "ckpt_bench",
        "state_mb": round(nbytes / (1024 * 1024), 3),
        "blocking_save_s": round(min(blocking_s), 6),
        "async_stall_s": round(stall_s, 6),
        "async_write_s": round(write_s, 6),
        "stall_vs_blocking": round(stall_s / min(blocking_s), 4)
        if min(blocking_s) else None,
        "overlap_ok": bool(stall_s < min(blocking_s)),
        "restore_exact": bool(exact),
        "overlapped_step_s": round(min(overlap_step_s), 6),
        "saves": saves,
        "fsdp": fsdp,
        "ckpt_records": profiler.ckpt_report(),
        "backend": jax.default_backend(),
    }


def run_input_bench(*, steps: int = 24, global_batch: int = 32,
                    hidden: int = 512, examples: int = 512,
                    feed_latency_ms: float = 3.0,
                    depths: tuple = (0, 1, 2)) -> dict:
    """Input-plane leg (tony_tpu.data): per-step wait-on-data at prefetch
    depth 0/1/2 over the SAME deterministic pipeline and train step.

    The pipeline's map stage sleeps ``feed_latency_ms`` per batch —
    simulated feed LATENCY (disk seek / decode wait / remote read), the
    component prefetch can hide on any backend (a CPU-bound map would
    contend with the XLA step on CPU and say nothing about TPU). Depth 0
    pays the latency inside every ``next()``; depth >= 1 stages batches
    from the background thread while the device steps, so the measured
    wait collapses to the queue pop. ``stall_hidden`` (depth-1 wait under
    half the depth-0 wait) gates the headline, mirroring ``overlap_ok``
    in the ckpt bench.
    """
    import numpy as np
    import optax

    from tony_tpu import data as data_mod
    from tony_tpu import parallel as par
    from tony_tpu import profiler
    from tony_tpu import train as tr
    from tony_tpu.models import get_model

    mesh = par.make_mesh()
    model = get_model("mnist-mlp", hidden=hidden)
    kx, ky, kr = jax.random.split(jax.random.PRNGKey(0), 3)
    x0 = jax.random.normal(kx, (global_batch, 784), jnp.float32)
    state0 = tr.create_train_state(model, optax.sgd(0.1, momentum=0.9),
                                   x0, kr)
    step = tr.make_train_step(mesh=mesh, donate=False)
    xs = np.asarray(jax.random.normal(kx, (examples, 784), jnp.float32))
    ys = np.asarray(jax.random.randint(ky, (examples,), 0, 10))

    def slow_map(batch):
        time.sleep(feed_latency_ms / 1e3)
        return batch

    def make_iter(depth):
        ds = (data_mod.Dataset.from_arrays({"x": xs, "y": ys}, seed=0)
              .shuffle().repeat().batch(global_batch).map(slow_map))
        return data_mod.DeviceIterator(
            ds.iterator(data_mod.ShardSpec(0, 1)), mesh, depth=depth,
            tag=f"input_d{depth}")

    profiler.reset_input_records()
    out: dict = {"metric": "input_bench", "global_batch": global_batch,
                 "steps": steps, "feed_latency_ms": feed_latency_ms,
                 "backend": jax.default_backend()}
    per_depth = {}
    for depth in depths:
        it = make_iter(depth)
        state = state0
        try:
            # Warm: compile the step and (depth >= 1) fill the staging
            # queue before the timed window.
            state, _ = step(state, next(it))
            jax.block_until_ready(state.params)
            n_warm = it.stats["steps"]
            warm_wait_s = it.stats["wait_s_total"]
            t0 = time.perf_counter()
            for _ in range(steps):
                state, _ = step(state, next(it))
            jax.block_until_ready(state.params)
            wall = time.perf_counter() - t0
            n_timed = it.stats["steps"] - n_warm
            timed_wait_s = it.stats["wait_s_total"] - warm_wait_s
            per_depth[depth] = {
                "step_ms": round(1e3 * wall / steps, 3),
                "input_wait_ms": round(1e3 * timed_wait_s / n_timed, 3),
            }
        finally:
            it.close()
    out["per_depth"] = {str(k): v for k, v in per_depth.items()}
    d0 = per_depth.get(0, {}).get("input_wait_ms")
    d1 = per_depth.get(1, {}).get("input_wait_ms")
    out["input_stall_ms_depth0"] = d0
    out["input_stall_ms_depth1"] = d1
    out["input_stall_ms_depth2"] = \
        per_depth.get(2, {}).get("input_wait_ms")
    out["stall_hidden"] = bool(d0 is not None and d1 is not None
                               and d1 < 0.5 * d0)
    out["input_records"] = profiler.input_report()
    return out


def peak_flops(on_tpu: bool | None = None) -> float:
    """THE peak-FLOPs rule for MFU accounting (single definition — every
    bench leg divides by this): the chip generation's bf16 peak on TPU, a
    1e12 sentinel off-TPU so CPU smoke runs produce obviously-not-TPU
    numbers. ``on_tpu=None`` derives from the live backend."""
    if on_tpu is None:
        on_tpu = jax.default_backend() not in ("cpu",)
    if not on_tpu:
        return 1e12
    return PEAK_BF16.get(chip_generation(), PEAK_BF16["v5e"])


def run_resnet_bench(batch: int, image: int, steps: int, *,
                     s2d: bool = True, fused_bn: bool = False,
                     on_tpu: bool | None = None) -> dict:
    """Measure and return the headline dict (metric/value/vs_baseline…).
    ``on_tpu`` defaults to backend auto-detection so every caller (bench.py
    AND the tony-submitted job) accounts MFU identically."""
    from tony_tpu.models.resnet import resnet50_flops

    if on_tpu is None:
        on_tpu = jax.default_backend() not in ("cpu",)
    window, carry = resnet_window(batch, image, steps, s2d=s2d,
                                  fused_bn=fused_bn)
    elapsed, carry, loss = best_window_time(window, carry,
                                            params_of=lambda c: c[0])
    images_per_sec = batch * steps / elapsed
    train_flops_per_step = 3 * resnet50_flops(batch, image)
    gen = chip_generation()
    peak = peak_flops(on_tpu)
    mfu = train_flops_per_step * steps / elapsed / peak
    return {
        "metric": "resnet50_mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_bf16_peak",
        "vs_baseline": round(mfu / 0.55, 4),
        "images_per_sec_per_chip": round(images_per_sec, 1),
        "batch": batch,
        "image": image,
        "backend": jax.default_backend(),
        "chip": gen,
        "fused_bn": fused_bn,
        "s2d_stem": s2d,
        "loss": float(loss),
    }


def run_quant_bench(*, m: int = 512, k: int = 1024, n: int = 1024,
                    steps: int | None = None,
                    on_tpu: bool | None = None) -> dict:
    """Quantized-lane leg (tony_tpu.ops.quant): three gated numbers.

    1. **Matmul wall time** — the int8×int8→int32+f32-rescale path vs the
       bf16 matmul at a projection-sized shape, both jitted and fenced
       best-of-N. On TPU metal the int8 MXU runs 2× bf16 peak
       (ROOFLINE.md §7); on the CPU simulation XLA has no int8 fast path,
       so the CPU number documents the dispatch overhead, not the win —
       ``quant_matmul_sim_note`` says so explicitly and the metal
       measurement rides the real-hardware debt list.
    2. **Quantize-on-gather bytes** — raw vs int8 wire bytes of the
       ZeRO-3 forward gathers from the live GatherPlan (the ≥2×-fewer-
       gather-bytes claim vs BENCH_r09's bucketed path; 4× for f32
       params), plus the bit-exactness pin (dequantized int8 gather ==
       quantize∘dequantize of the unquantized gather).
    3. **Loss pin** — a short quantized-gather accum training vs the
       unquantized one; the relative final-loss disagreement gates the
       byte claim the way ``numerics_ok`` gates every other leg.
    """
    import numpy as np
    import optax

    from tony_tpu import parallel as par
    from tony_tpu import profiler
    from tony_tpu import train as tr
    from tony_tpu.models import get_model
    from tony_tpu.ops import quant as q
    from tony_tpu.parallel import overlap

    if on_tpu is None:
        on_tpu = jax.default_backend() not in ("cpu",)
    if steps is None:
        steps = 20 if on_tpu else 8
    windows = int(os.environ.get("BENCH_WINDOWS", "3"))
    ks = jax.random.split(jax.random.PRNGKey(0), 2)
    x16 = jax.random.normal(ks[0], (m, k), jnp.bfloat16)
    w16 = jax.random.normal(ks[1], (k, n), jnp.bfloat16) * 0.2

    bf16_jit = jax.jit(lambda a, b: a @ b)
    quant_jit = jax.jit(functools.partial(q.quant_dot, impl=None))

    def timed(fn, *args):
        fn(*args).block_until_ready()          # compile
        fn(*args).block_until_ready()          # steady state
        best = float("inf")
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(steps):
                out = fn(*args)
            out.block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best / steps

    bf16_s = timed(bf16_jit, x16, w16)
    quant_s = timed(quant_jit, x16, w16)
    # Kernel-vs-fallback pin at a small shape (interpret mode compiles
    # the whole padded grid on CPU — keep it cheap).
    xs = jax.random.normal(ks[0], (33, 70), jnp.float32)
    ws = jax.random.normal(ks[1], (70, 130), jnp.float32)
    kernel_bitexact = bool(np.array_equal(
        np.asarray(q.quant_dot(xs, ws, impl="xla")),
        np.asarray(q.quant_dot(xs, ws, interpret=True))))

    # --- quantize-on-gather: bytes + exactness + loss pin -------------
    n_dev = len(jax.devices())
    fsdp = 4 if n_dev % 4 == 0 else (2 if n_dev % 2 == 0 else 1)
    out: dict = {
        "metric": "quant_bench",
        "matmul_m_k_n": [m, k, n],
        "bf16_matmul_s": round(bf16_s, 6),
        "quant_matmul_s": round(quant_s, 6),
        "quant_matmul_speedup": round(bf16_s / quant_s, 4)
        if quant_s else None,
        "quant_kernel_bitexact": kernel_bitexact,
        "backend": jax.default_backend(),
    }
    if not on_tpu:
        out["quant_matmul_sim_note"] = (
            "CPU simulation: XLA has no int8 matmul fast path, so the "
            "wall-time ratio here measures quantize/rescale overhead, "
            "not the MXU win — int8 doubles MXU peak on metal "
            "(ROOFLINE.md §7); measurement rides the real-hardware "
            "debt list (ROADMAP)")
    if fsdp < 2:
        return out

    mesh = par.make_mesh(fsdp=fsdp)
    model = get_model("mnist-mlp", hidden=64)
    kx, ky = jax.random.split(jax.random.PRNGKey(1))
    data = {"x": jax.random.normal(kx, (64, 784), jnp.float32),
            "y": jax.random.randint(ky, (64,), 0, 10)}
    bb = 1 << 15

    def fresh():
        return fsdp_shard_state(tr.create_train_state(
            model, optax.adamw(1e-3), data["x"], jax.random.PRNGKey(2)),
            mesh)

    profiler.reset_quant_records()
    sp = fresh()
    sq = q.with_gather_quant(fresh(), mesh, window=4, bucket_bytes=bb)
    specs = overlap.fsdp_param_specs(sq.params, mesh)
    plan, gplan = overlap.step_plans(sq.params, mesh, bucket_bytes=bb,
                                     param_specs=specs)
    raw = sum(gplan.gather_nbytes)
    int8 = sum(plan.bucket_numel[b] for b in gplan.gather_buckets)
    step_p = tr.make_accum_train_step(mesh=mesh, microbatches=4,
                                      bucket_bytes=bb, donate=False)
    step_q = tr.make_accum_train_step(mesh=mesh, microbatches=4,
                                      bucket_bytes=bb, quant=True,
                                      donate=False)
    for _ in range(steps):
        sp, mp = step_p(sp, data)
        sq, mq = step_q(sq, data)
    lp, lq = float(mp["loss"]), float(mq["loss"])
    out.update({
        "gather_raw_nbytes": raw,
        "gather_int8_nbytes": int8,
        "gather_bytes_ratio": round(raw / int8, 2) if int8 else None,
        "gather_2x_fewer_ok": bool(int8 and raw / int8 >= 2.0),
        "gather_roundtrip_bitexact": q.gather_roundtrip_exact(
            sq.params, mesh, bb),
        "losspin_steps": steps,
        "losspin_plain": round(lp, 6),
        "losspin_quant": round(lq, 6),
        "losspin_rel": round(abs(lq - lp) / lp, 6) if lp else None,
        "losspin_ok": bool(lp and abs(lq - lp) / lp < 0.02),
        "fsdp": fsdp,
        "quant_records": profiler.quant_report(),
    })
    return out


def _drive_serve_trace(eng, prompts, new_tokens, arrivals,
                       warm_prompts=None, tenants=None) -> dict:
    """The shared arrival-driven measurement loop of the serve, spec,
    and route bench legs — ONE implementation so the legs can claim
    "the same Poisson trace" structurally, not by parallel maintenance.
    Warms every jit shape the trace will hit (max_new_tokens=2 — the
    measured window times steady-state engine behavior, not compiles),
    snapshots every counter the caller reads (forwards, draft forwards,
    the speculation counters — the warm pass runs at forced depth
    min(k, remaining)=1 and must not dilute the per-depth numbers —
    and the route leg's prefill/prefix counters), then replays
    ``arrivals`` in wall time and reports tokens, latencies, and
    warm-excluded counter deltas. ``warm_prompts`` overrides the warm
    pass's prompts (the route leg warms with length-matched but
    token-scrambled prompts so the prefix cache's measured hit rate
    comes from the trace's OWN sharing, not from the warm pass having
    pre-published the very prompts under test). ``tenants`` tags each
    request's QoS class for the qos leg (the warm pass stays untagged —
    untagged requests bypass budgets, so warming never defers)."""
    import numpy as np

    from tony_tpu.serve import Request

    for i, p in enumerate(warm_prompts if warm_prompts is not None
                          else prompts):
        eng.submit(Request(rid=f"warm-{i}", tokens=p, max_new_tokens=2))
    eng.run()
    warm_forwards = eng.forwards
    warm_draft = getattr(getattr(eng, "draft", None), "forwards", 0)
    warm_spec = {k: getattr(eng, k, 0) for k in
                 ("spec_proposed", "spec_accepted", "spec_rounds",
                  "spec_tokens_out")}
    warm_route = {k: getattr(eng, k, 0) for k in
                  ("prefill_launches", "prefill_rows", "prefill_chunks",
                   "prefix_hit_blocks", "prefix_lookup_blocks")}
    warm_steps = eng._steps
    done: dict = {}
    i = 0
    t0 = time.perf_counter()
    while i < len(prompts) or eng.queue_depth or eng.running:
        now = time.perf_counter() - t0
        while i < len(prompts) and now >= arrivals[i]:
            eng.submit(Request(rid=f"r{i}", tokens=prompts[i],
                               max_new_tokens=new_tokens[i],
                               tenant=(None if tenants is None
                                       else tenants[i])))
            i += 1
        if not (eng.queue_depth or eng.running):
            time.sleep(max(0.0, arrivals[i] - now))
            continue
        for c in eng.step():
            done[c.rid] = c
    wall = time.perf_counter() - t0
    lats = sorted(c.latency_s for c in done.values())

    def pct(p):
        return lats[min(len(lats) - 1, int(p * (len(lats) - 1) + 0.5))]

    n_tokens = sum(len(c.tokens) for c in done.values())
    forwards = eng.forwards - warm_forwards
    out = {
        "tokens": {rid: c.tokens for rid, c in done.items()},
        "wall_s": wall,
        "tokens_per_s": n_tokens / wall,
        "p50_ms": 1e3 * pct(0.50),
        "p99_ms": 1e3 * pct(0.99),
        # Per-request latency map: the disagg leg slices the decode
        # floor out of a mixed floor+burst trace.
        "latency_ms": {rid: 1e3 * c.latency_s
                       for rid, c in done.items()},
        "forwards": forwards,
        "steps": eng._steps - warm_steps,
        "tokens_per_forward": n_tokens / forwards,
    }
    route = {k: getattr(eng, k, 0) - warm_route[k] for k in warm_route}
    out["prefill_launches"] = route["prefill_launches"]
    out["prefill_rows"] = route["prefill_rows"]
    out["prefill_chunks"] = route["prefill_chunks"]
    out["prefix_hit_rate"] = (
        route["prefix_hit_blocks"] / route["prefix_lookup_blocks"]
        if route["prefix_lookup_blocks"] else 0.0)
    if hasattr(eng, "spec_proposed"):
        proposed = eng.spec_proposed - warm_spec["spec_proposed"]
        accepted = eng.spec_accepted - warm_spec["spec_accepted"]
        rounds = eng.spec_rounds - warm_spec["spec_rounds"]
        spec_tokens = eng.spec_tokens_out - warm_spec["spec_tokens_out"]
        out["draft_forwards"] = (
            getattr(eng.draft, "forwards", 0) - warm_draft)
        out["acceptance_rate"] = (accepted / proposed
                                  if proposed else 0.0)
        out["tokens_per_seq_round"] = (spec_tokens / rounds
                                       if rounds else 0.0)
    return out


def run_serve_bench(*, n_requests: int | None = None,
                    max_new: int | None = None, seed: int = 0,
                    on_tpu: bool | None = None) -> dict:
    """Serving-plane leg (tony_tpu.serve): continuous vs static batching
    under one Poisson arrival trace on the simulated mesh.

    Both policies run the SAME engine, model, params, and arrival
    schedule; the only difference is the join rule — continuous admits a
    request the iteration blocks free up, static waits for the running
    batch to drain (the classic serve-a-batch-at-a-time baseline every
    user would rebuild). Three gated numbers:

    * **tokens/s** per policy and the continuous/static throughput
      ratio;
    * **p50/p99 request latency** per policy (arrival→completion wall
      time — the number the heartbeat autoscaler acts on);
    * **numerics gate** — both policies must emit IDENTICAL token
      streams per request (continuous batching is bit-transparent; the
      serve test suite pins the logits, this leg gates the tokens).

    CPU-simulated wall times measure engine/dispatch behavior, not TPU
    decode throughput — ``serve_sim_note`` says so; metal numbers ride
    the real-hardware debt list.
    """
    import numpy as np

    import flax.linen as nn

    from tony_tpu.models import get_model
    from tony_tpu.serve import Request, ServeEngine

    if on_tpu is None:
        on_tpu = jax.default_backend() not in ("cpu",)
    if n_requests is None:
        n_requests = 24
    rng = np.random.RandomState(seed)
    model = get_model("llama-tiny", n_layers=2)
    toks0 = jnp.zeros((1, 16), jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(seed), toks0))["params"]
    params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        params)
    prompts = [list(rng.randint(0, model.cfg.vocab, rng.randint(4, 24)))
               for _ in range(n_requests)]
    # Heterogeneous generation lengths: the head-of-line blocking that
    # batch-boundary ("static") serving suffers — a short request stuck
    # behind a long batch — is the regime iteration-level join/evict
    # exists for.
    new_tokens = [int(rng.randint(2, 25)) if max_new is None else max_new
                  for _ in range(n_requests)]

    def drive(policy: str, gap_s: float) -> dict:
        eng = ServeEngine(model, params, ctx_max=64, block_size=8,
                          q_block=16, decode_buckets=(8,), max_running=8,
                          join_policy=policy, tag=f"serve_bench_{policy}")
        # Poisson arrivals in WALL time (mean gap scaled off a measured
        # decode step, so requests land while earlier ones still decode
        # — the regime continuous batching exists for, on any backend),
        # drawn per policy off the shared rng exactly as before the
        # drive loop moved into _drive_serve_trace.
        arrivals = np.cumsum(rng.exponential(gap_s, n_requests))
        return _drive_serve_trace(eng, prompts, new_tokens, arrivals)

    # Calibrate the arrival rate off a measured decode step so the trace
    # overlaps generations on fast and slow backends alike: one request
    # occupies the engine for ~(1 prefill + max_new-1 decodes); a mean
    # gap of ~1.5 decode steps keeps several generations in flight.
    probe = ServeEngine(model, params, ctx_max=64, block_size=8,
                        q_block=16, decode_buckets=(8,), max_running=8,
                        tag="serve_bench_probe")
    probe.submit(Request(rid="probe", tokens=prompts[0],
                         max_new_tokens=4))
    probe.run()
    t0 = time.perf_counter()
    probe.submit(Request(rid="probe2", tokens=prompts[0],
                         max_new_tokens=4))
    steps0 = probe._steps
    probe.run()
    step_s = (time.perf_counter() - t0) / max(1, probe._steps - steps0)
    gap_s = 1.5 * step_s
    cont = drive("continuous", gap_s)
    stat = drive("static", gap_s)
    out = {
        "serve_requests": n_requests,
        "serve_max_new_tokens": (max_new if max_new is not None
                                 else [min(new_tokens), max(new_tokens)]),
        "serve_continuous_tokens_per_s": round(cont["tokens_per_s"], 2),
        "serve_static_tokens_per_s": round(stat["tokens_per_s"], 2),
        "serve_throughput_ratio": round(
            cont["tokens_per_s"] / stat["tokens_per_s"], 3)
        if stat["tokens_per_s"] else None,
        "serve_continuous_forwards": cont["forwards"],
        "serve_static_forwards": stat["forwards"],
        "serve_forwards_ratio": round(
            stat["forwards"] / cont["forwards"], 3)
        if cont["forwards"] else None,
        "serve_continuous_p50_ms": round(cont["p50_ms"], 2),
        "serve_continuous_p99_ms": round(cont["p99_ms"], 2),
        "serve_static_p50_ms": round(stat["p50_ms"], 2),
        "serve_static_p99_ms": round(stat["p99_ms"], 2),
        "serve_numerics_ok": cont["tokens"] == stat["tokens"],
        "backend": jax.default_backend(),
    }
    if not on_tpu:
        out["serve_sim_note"] = (
            "CPU simulation: wall times are noisy and biased against "
            "the continuous policy (alternating prefill/decode "
            "executables run ~2x slower per launch on XLA CPU than a "
            "same-executable streak — a host artifact; on TPU the "
            "forward dominates and launch cost is shape-stable). The "
            "machine-independent claim is serve_forwards_ratio: fewer "
            "forward launches for the SAME tokens under the same trace. "
            "Metal wall numbers ride the real-hardware debt list "
            "(ROADMAP)")
    return out


def run_spec_bench(*, n_requests: int | None = None,
                   depths: tuple = (2, 4, 8), seed: int = 0,
                   on_tpu: bool | None = None) -> dict:
    """Speculative-decoding leg (tony_tpu.serve.spec): the draft-and-
    verify engine vs the plain continuous-batching engine on the SAME
    Poisson arrival trace as BENCH_r12 (same seed, same prompts, same
    generation lengths, same calibration protocol). Gated numbers:

    * **tokens per target forward** — the headline: speculation must
      multiply what one target launch buys. Two views: the global
      ``tokens_per_forward`` (prefills included) against the baseline's,
      and the per-sequence ``tokens_per_seq_round`` (= 1 + mean accepted
      run — what ONE verify launch earns for ONE sequence, batching
      excluded; > 1 whenever anything is accepted);
    * **acceptance rate by draft depth k** — the self-drafting n-gram
      lane at each k (no second model needed; greedy tails of the tiny
      model repeat, which is exactly what prompt lookup predicts), plus
      the draft==target model lane as the perfect-acceptance upper
      bound with its draft forwards accounted;
    * **the bitwise gate** — every configuration must emit token streams
      IDENTICAL to the plain engine's (greedy accept/reject is
      deterministic; tests/test_spec.py pins the logits too).

    CPU-simulated wall times measure engine scheduling, not TPU decode —
    ``spec_sim_note`` says so; metal rides the real-hardware debt list.
    """
    import numpy as np

    import flax.linen as nn

    from tony_tpu.models import get_model
    from tony_tpu.serve import Request, ServeEngine, SpecEngine

    if on_tpu is None:
        on_tpu = jax.default_backend() not in ("cpu",)
    if n_requests is None:
        n_requests = 24
    rng = np.random.RandomState(seed)
    model = get_model("llama-tiny", n_layers=2)
    toks0 = jnp.zeros((1, 16), jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(seed), toks0))["params"]
    params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        params)
    # The BENCH_r12 trace, reproduced: same RandomState consumption order.
    prompts = [list(rng.randint(0, model.cfg.vocab, rng.randint(4, 24)))
               for _ in range(n_requests)]
    new_tokens = [int(rng.randint(2, 25)) for _ in range(n_requests)]

    def build(kind: str, k: int = 0):
        kw = dict(ctx_max=64, block_size=8, q_block=16,
                  decode_buckets=(8,), max_running=8,
                  tag=f"spec_bench_{kind}{k or ''}")
        if kind == "plain":
            return ServeEngine(model, params, **kw)
        if kind == "ngram":
            return SpecEngine(model, params, spec_k=k, **kw)
        return SpecEngine(model, params, spec_k=k, draft_model=model,
                          draft_params=params, **kw)

    # The BENCH_r12 calibration protocol: mean arrival gap ~1.5 measured
    # engine steps, so generations overlap on fast and slow backends.
    probe = build("plain")
    probe.tag = "spec_bench_probe"
    probe.submit(Request(rid="probe", tokens=prompts[0],
                         max_new_tokens=4))
    probe.run()
    t0 = time.perf_counter()
    probe.submit(Request(rid="probe2", tokens=prompts[0],
                         max_new_tokens=4))
    steps0 = probe._steps
    probe.run()
    step_s = (time.perf_counter() - t0) / max(1, probe._steps - steps0)
    gap_s = 1.5 * step_s

    # ONE arrival schedule, shared by every engine — forward counts
    # compare speculation against the baseline on the identical trace,
    # not against Poisson draw noise (wall-clock join timing still
    # jitters batch composition, but greedy token streams are
    # arrival-independent, which is what the bitwise gate checks).
    arrivals = np.cumsum(rng.exponential(gap_s, n_requests))
    base = _drive_serve_trace(build("plain"), prompts, new_tokens,
                              arrivals)
    out = {
        "metric": "spec_bench",
        "spec_requests": n_requests,
        "spec_baseline_forwards": base["forwards"],
        "spec_baseline_tokens_per_forward": round(
            base["tokens_per_forward"], 3),
        "spec_baseline_p50_ms": round(base["p50_ms"], 2),
        "spec_baseline_p99_ms": round(base["p99_ms"], 2),
        "spec_baseline_tokens_per_s": round(base["tokens_per_s"], 2),
        "backend": jax.default_backend(),
    }
    all_identical = True
    for k in depths:
        r = _drive_serve_trace(build("ngram", k), prompts,
                               new_tokens, arrivals)
        ident = r["tokens"] == base["tokens"]
        all_identical = all_identical and ident
        out[f"spec_k{k}_forwards"] = r["forwards"]
        out[f"spec_k{k}_forwards_ratio"] = round(
            base["forwards"] / r["forwards"], 3)
        out[f"spec_k{k}_tokens_per_forward"] = round(
            r["tokens_per_forward"], 3)
        out[f"spec_k{k}_tokens_per_seq_round"] = round(
            r["tokens_per_seq_round"], 3)
        out[f"spec_k{k}_acceptance_rate"] = round(
            r["acceptance_rate"], 3)
        out[f"spec_k{k}_p50_ms"] = round(r["p50_ms"], 2)
        out[f"spec_k{k}_p99_ms"] = round(r["p99_ms"], 2)
        out[f"spec_k{k}_tokens_identical"] = ident
    # Perfect-draft upper bound: draft == target, total acceptance —
    # what a well-trained small draft buys at this depth (its launches
    # are a same-size model here; a real draft is k× smaller, which is
    # the point — see spec_sim_note).
    ub = _drive_serve_trace(build("model", 4), prompts,
                            new_tokens, arrivals)
    out["spec_selfdraft_forwards"] = ub["forwards"]
    out["spec_selfdraft_draft_forwards"] = ub["draft_forwards"]
    out["spec_selfdraft_forwards_ratio"] = round(
        base["forwards"] / ub["forwards"], 3)
    out["spec_selfdraft_acceptance_rate"] = round(
        ub["acceptance_rate"], 3)
    out["spec_selfdraft_tokens_per_seq_round"] = round(
        ub["tokens_per_seq_round"], 3)
    out["spec_selfdraft_tokens_identical"] = \
        ub["tokens"] == base["tokens"]
    out["spec_numerics_ok"] = all_identical and \
        out["spec_selfdraft_tokens_identical"]
    if not on_tpu:
        out["spec_sim_note"] = (
            "CPU simulation: wall clock measures engine scheduling, not "
            "TPU decode. The machine-independent claims are the forward "
            "counts: spec_k*_forwards_ratio (fewer target launches for "
            "the SAME tokens on the same trace) and tokens_per_seq_round "
            "(= 1 + mean accepted run, what one verify launch earns one "
            "sequence). The n-gram lane costs zero extra launches; the "
            "selfdraft lane's draft launches are a SAME-size model here "
            "(upper-bound acceptance demo) — a production draft is "
            "several times smaller, so its launches cost a fraction of "
            "a target forward. Metal wall numbers ride the "
            "real-hardware debt list (ROADMAP)")
    return out


def _drive_routed_trace(router, prompts, new_tokens, arrivals,
                        sessions=None, refresh=None) -> dict:
    """Arrival-driven drive through a :class:`tony_tpu.serve.router.
    RequestRouter`: one thread per request sleeps until its arrival and
    dispatches; the in-process EngineFront transports interleave the
    concurrent callers onto each replica's continuous batch — the same
    drive discipline a replica's RPC front runs. ``refresh`` (called
    before each dispatch) stands in for the heartbeat tick: it pushes
    each replica's live queue/p99/digest into the router, so the
    scoring sees the fleet as the AM would."""
    import threading

    results: dict = {}
    walls: dict = {}
    lock = threading.Lock()
    t0 = time.perf_counter()

    def worker(i: int) -> None:
        delay = arrivals[i] - (time.perf_counter() - t0)
        if delay > 0:
            time.sleep(delay)
        if refresh is not None:
            with lock:
                refresh()
        t_req = time.perf_counter()
        out = router.dispatch(
            prompts[i], new_tokens[i], rid=f"r{i}",
            session_id=None if sessions is None else sessions[i])
        with lock:
            results[f"r{i}"] = out
            # Caller-side wall latency: arrival -> completion INCLUDING
            # routing and (for a disaggregated fleet) the KV handoff —
            # the replica-reported latency_ms covers only its own
            # engine's window.
            walls[f"r{i}"] = 1e3 * (time.perf_counter() - t_req)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lats = sorted(r["latency_ms"] for r in results.values())

    def pct(p):
        return lats[min(len(lats) - 1, int(p * (len(lats) - 1) + 0.5))]

    n_tokens = sum(len(r["tokens"]) for r in results.values())
    by_replica: dict = {}
    for r in results.values():
        by_replica[r["replica"]] = by_replica.get(r["replica"], 0) + 1
    return {
        "tokens": {rid: r["tokens"] for rid, r in results.items()},
        "wall_s": wall,
        "tokens_per_s": n_tokens / wall,
        "p50_ms": pct(0.50),
        "p99_ms": pct(0.99),
        "wall_latency_ms": dict(walls),
        "by_replica": by_replica,
    }


def run_route_bench(*, n_requests: int | None = None, seed: int = 0,
                    on_tpu: bool | None = None) -> dict:
    """Routed-serving leg (tony_tpu.serve PR 13) on a shared-prefix
    workload mix: chat-style traffic where most prompts extend one of a
    few long system-prompt stems — the regime where prefill compute is
    mostly redundant re-processing of shared prefixes. Four engine
    configurations run the SAME requests (prefix caching and chunked
    prefill are bit-transparent, so the token-identity gate holds
    across all of them), then the same trace runs ROUTED over a
    2-replica fleet:

    * **prefill-launch/row reduction + cache hit rate** (the
      machine-independent claims): with the prefix cache on, admissions
      adopt the published stem blocks and the corresponding prefill
      work is never issued;
    * **p50/p99 with chunked prefill on vs off** under long-prompt
      admissions landing mid-decode;
    * **2-replica routed vs 1-replica throughput** with sticky
      sessions and digest-driven cache affinity;
    * **the numerics gate** — every configuration (and the routed
      fleet) must emit IDENTICAL token streams per request.

    CPU wall numbers measure engine scheduling (``route_sim_note``);
    the launch/row counts and hit rates are the claims that transfer.
    """
    import numpy as np

    import flax.linen as nn

    from tony_tpu.models import get_model
    from tony_tpu.serve import EngineFront, Request, ServeEngine
    from tony_tpu.serve.router import RequestRouter

    if on_tpu is None:
        on_tpu = jax.default_backend() not in ("cpu",)
    if n_requests is None:
        n_requests = 24
    rng = np.random.RandomState(seed)
    model = get_model("llama-tiny", n_layers=2)
    toks0 = jnp.zeros((1, 16), jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(seed), toks0))["params"]
    params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        params)
    # The shared-prefix mix: 3 "system prompt" stems of 32 tokens (4 KV
    # blocks of 8), each request = a stem + a unique 1..16-token tail;
    # sessions group requests per stem so sticky routing keeps a
    # conversation's blocks on one replica.
    stems = [list(rng.randint(0, model.cfg.vocab, 32)) for _ in range(3)]
    stem_of = [int(rng.randint(3)) for _ in range(n_requests)]
    prompts = [stems[s] + list(rng.randint(0, model.cfg.vocab,
                                           1 + int(rng.randint(16))))
               for s in stem_of]
    sessions = [f"sess-{s}" for s in stem_of]
    new_tokens = [int(rng.randint(2, 17)) for _ in range(n_requests)]
    # Length-matched scrambled warm prompts: compile every shape the
    # trace hits WITHOUT pre-publishing the measured prompts' blocks —
    # the reported hit rate is the trace's own sharing.
    warm_prompts = [list(rng.randint(0, model.cfg.vocab, len(p)))
                    for p in prompts]

    def build(tag: str, **kw) -> ServeEngine:
        return ServeEngine(model, params, ctx_max=64, block_size=8,
                           q_block=16, decode_buckets=(8,), max_running=8,
                           tag=f"route_bench_{tag}", **kw)

    # BENCH_r12/r13 calibration protocol: mean arrival gap ~1.5 measured
    # engine steps so generations overlap on any backend.
    probe = build("probe")
    probe.submit(Request(rid="probe", tokens=prompts[0],
                         max_new_tokens=4))
    probe.run()
    t0 = time.perf_counter()
    probe.submit(Request(rid="probe2", tokens=prompts[0],
                         max_new_tokens=4))
    steps0 = probe._steps
    probe.run()
    step_s = (time.perf_counter() - t0) / max(1, probe._steps - steps0)
    arrivals = np.cumsum(rng.exponential(1.5 * step_s, n_requests))

    configs = {
        "base": {},
        "prefix": {"prefix_cache": True},
        "chunk": {"prefill_chunk": 32},
        "prefix_chunk": {"prefix_cache": True, "prefill_chunk": 32},
    }
    runs = {name: _drive_serve_trace(build(name, **kw), prompts,
                                     new_tokens, arrivals,
                                     warm_prompts=warm_prompts)
            for name, kw in configs.items()}
    base = runs["base"]
    out = {
        "metric": "route_bench",
        "route_requests": n_requests,
        "route_stems": len(stems),
        "route_stem_tokens": len(stems[0]),
        "backend": jax.default_backend(),
    }
    identical = True
    for name, r in runs.items():
        identical = identical and r["tokens"] == base["tokens"]
        out[f"route_{name}_prefill_launches"] = r["prefill_launches"]
        out[f"route_{name}_prefill_rows"] = r["prefill_rows"]
        out[f"route_{name}_p50_ms"] = round(r["p50_ms"], 2)
        out[f"route_{name}_p99_ms"] = round(r["p99_ms"], 2)
        out[f"route_{name}_tokens_per_s"] = round(r["tokens_per_s"], 2)
    out["route_prefix_hit_rate"] = round(runs["prefix"]["prefix_hit_rate"],
                                         3)
    out["route_prefix_chunk_hit_rate"] = round(
        runs["prefix_chunk"]["prefix_hit_rate"], 3)
    # The prefill-forward-launch reduction: measured on the chunked
    # pair, where a launch is a fixed chunk of work — adopting a stem's
    # blocks skips whole chunk launches. (Monolithic prefill always
    # costs one launch per admission; there the saving shows in ROWS.)
    out["route_prefix_launch_reduction"] = round(
        runs["chunk"]["prefill_launches"]
        / runs["prefix_chunk"]["prefill_launches"], 3) \
        if runs["prefix_chunk"]["prefill_launches"] else None
    out["route_prefix_row_reduction"] = round(
        base["prefill_rows"] / runs["prefix"]["prefill_rows"], 3) \
        if runs["prefix"]["prefill_rows"] else None

    # -- the 2-replica routed fleet vs the 1-replica baseline ------------
    def routed(n_replicas: int) -> dict:
        router = RequestRouter(block_size=8)
        engines = []
        for i in range(n_replicas):
            eng = build(f"fleet{n_replicas}_{i}", prefix_cache=True,
                        prefill_chunk=32)
            # Warm each replica's shapes outside the measured window.
            front = EngineFront(eng)
            for w in (warm_prompts[0], warm_prompts[1]):
                front.generate(w, 2)
            engines.append(eng)
            router.upsert_replica(f"r{i}", client=front,
                                  stats=eng.stats())

        def refresh() -> None:
            # The heartbeat tick, inlined: live queue depth + digest.
            for i, e in enumerate(engines):
                router.upsert_replica(f"r{i}", stats={
                    **e.stats(), "prefix_digest": e.prefix_digest()})

        run = _drive_routed_trace(router, prompts, new_tokens, arrivals,
                                  sessions=sessions, refresh=refresh)
        run["router_stats"] = router.stats()
        run["forwards"] = sum(e.forwards for e in engines)
        return run

    one = routed(1)
    two = routed(2)
    out["route_1rep_tokens_per_s"] = round(one["tokens_per_s"], 2)
    out["route_2rep_tokens_per_s"] = round(two["tokens_per_s"], 2)
    out["route_2rep_speedup"] = round(
        two["tokens_per_s"] / one["tokens_per_s"], 3) \
        if one["tokens_per_s"] else None
    out["route_2rep_p50_ms"] = round(two["p50_ms"], 2)
    out["route_2rep_p99_ms"] = round(two["p99_ms"], 2)
    out["route_2rep_by_replica"] = two["by_replica"]
    out["route_2rep_affinity_hits"] = two["router_stats"]["affinity_hits"]
    out["route_2rep_cache_routed"] = two["router_stats"]["cache_routed"]
    identical = identical and one["tokens"] == base["tokens"] \
        and two["tokens"] == base["tokens"]
    out["route_numerics_ok"] = identical
    if not on_tpu:
        out["route_sim_note"] = (
            "CPU simulation: wall times measure engine scheduling on a "
            "shared host CPU (two 'replicas' contend for the same "
            "cores, so route_2rep_speedup understates a real fleet "
            "where each replica owns its chips; the monolithic+prefix "
            "config's wall numbers also suffer BENCH_r12's XLA-CPU "
            "executable-alternation artifact — prefix hits shrink each "
            "prefill to a different small shape, and alternating "
            "executables run ~2x slower per launch on CPU, which is "
            "why the chunked+prefix config, whose launches stay "
            "shape-stable, is the fast one). The machine-"
            "independent claims are route_prefix_launch_reduction / "
            "route_prefix_row_reduction (prefill work never issued for "
            "adopted blocks), route_prefix_hit_rate, and "
            "route_numerics_ok (identical token streams in every "
            "configuration, routed fleet included). Metal wall numbers "
            "ride the real-hardware debt list (ROADMAP)")
    return out


def run_disagg_bench(*, n_floor: int | None = None,
                     n_burst: int | None = None, seed: int = 0,
                     on_tpu: bool | None = None) -> dict:
    """Disaggregated prefill/decode leg (tony_tpu.serve.disagg, PR 15)
    on the shared Poisson protocol with a PREFILL-BURST phase: a steady
    decode floor (short prompts, long generations) absorbs a cluster of
    long-prompt admissions mid-trace — the regime where prefill and
    decode contend for the same chips. Two configurations run the SAME
    requests and arrival schedule:

    * **colocated chunked** — the BENCH_r14 mitigation: one engine,
      chunked prefill interleaved with decode (the decode floor pays
      one chunk launch per iteration while the burst drains);
    * **split gang** — a prefill replica and a decode replica behind
      the role-aware router: the burst's chunk launches run on the
      prefill replica, KV blocks ship over the handoff wire, and the
      decode replica's loop issues ZERO prefill work.

    The headline is decode-floor p99 isolation under the burst; the
    machine-independent claims are the decode side's prefill-launch
    count (exactly zero) and the forward-launch split; token identity
    is gated in both configurations (the handoff is bitwise
    transparent). CPU wall numbers measure scheduling on a shared host
    (``disagg_sim_note``)."""
    import numpy as np

    import flax.linen as nn

    from tony_tpu.models import get_model
    from tony_tpu.serve import EngineFront, Request, ServeEngine
    from tony_tpu.serve.disagg import DecodeFront, PrefillFront
    from tony_tpu.serve.router import RequestRouter

    if on_tpu is None:
        on_tpu = jax.default_backend() not in ("cpu",)
    if n_floor is None:
        n_floor = 16
    if n_burst is None:
        n_burst = 8
    burst_len = 96                      # 3 chunk launches per admission
    rng = np.random.RandomState(seed)
    model = get_model("llama-tiny", n_layers=2)
    toks0 = jnp.zeros((1, 16), jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(seed), toks0))["params"]
    params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        params)

    def build(tag: str, **kw) -> ServeEngine:
        return ServeEngine(model, params, ctx_max=128, block_size=8,
                           q_block=16, decode_buckets=(8,), max_running=8,
                           tag=f"disagg_bench_{tag}", **kw)

    # The workload: a decode floor of short prompts with real
    # generation lengths (the BENCH_r12/r13/r14 protocol), plus a burst
    # of long prompts — one chunk-launch apiece per 32 rows — landing
    # in a tight cluster one third into the trace: the regime where a
    # colocated engine interleaves the burst's chunk launches into
    # every decode iteration of the floor, and the split gang runs them
    # on the prefill replica instead.
    floor_prompts = [list(rng.randint(0, model.cfg.vocab,
                                      4 + int(rng.randint(9))))
                     for _ in range(n_floor)]
    floor_new = [int(rng.randint(10, 17)) for _ in range(n_floor)]
    burst_prompts = [list(rng.randint(0, model.cfg.vocab, burst_len))
                     for _ in range(n_burst)]
    burst_new = [int(rng.randint(2, 4)) for _ in range(n_burst)]

    # BENCH_r12/r13/r14 calibration protocol: arrival gaps scaled off a
    # measured engine step so the floor overlaps itself on any backend.
    probe = build("probe", prefill_chunk=32)
    probe.submit(Request(rid="probe", tokens=floor_prompts[0],
                         max_new_tokens=4))
    probe.run()
    t0 = time.perf_counter()
    probe.submit(Request(rid="probe2", tokens=floor_prompts[0],
                         max_new_tokens=4))
    steps0 = probe._steps
    probe.run()
    step_s = (time.perf_counter() - t0) / max(1, probe._steps - steps0)
    floor_arrivals = np.cumsum(rng.exponential(1.5 * step_s, n_floor))
    t_burst = float(floor_arrivals[n_floor // 3])
    burst_arrivals = t_burst + 0.1 * step_s * np.arange(n_burst)

    # One merged trace, sorted by arrival, floor membership remembered
    # by rid so the percentile split survives the sort.
    merged = sorted(
        [(a, p, n, True) for a, p, n in zip(floor_arrivals,
                                            floor_prompts, floor_new)]
        + [(a, p, n, False) for a, p, n in zip(burst_arrivals,
                                               burst_prompts, burst_new)],
        key=lambda t: t[0])
    arrivals = [t[0] for t in merged]
    prompts = [t[1] for t in merged]
    new_tokens = [t[2] for t in merged]
    floor_rids = [f"r{i}" for i, t in enumerate(merged) if t[3]]
    burst_rids = [f"r{i}" for i, t in enumerate(merged) if not t[3]]
    warm_prompts = [list(rng.randint(0, model.cfg.vocab, len(p)))
                    for p in prompts]

    def pctl(vals, p):
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(p * (len(vals) - 1) + 0.5))]

    # -- colocated chunked (the PR 13 mitigation) ------------------------
    col_eng = build("colocated", prefill_chunk=32)
    col = _drive_serve_trace(col_eng, prompts, new_tokens, arrivals,
                             warm_prompts=warm_prompts)

    # -- the split gang --------------------------------------------------
    pf_eng = build("prefill", role="prefill", prefill_chunk=32)
    dc_eng = build("decode", role="decode")
    pf_front, dc_front = EngineFront(pf_eng), EngineFront(dc_eng)
    pf_client = PrefillFront(pf_front)
    dc_client = DecodeFront(dc_front)
    # Warm every shape the trace hits THROUGH the handoff path (the
    # measured window times steady state, not compiles): one floor-
    # and one burst-shaped prompt.
    for wp in (warm_prompts[0],
               next(w for w, t in zip(warm_prompts, merged) if not t[3])):
        pf_client.prefill_handoff(wp, 2, decode=dc_client)
    warm = {"pf_forwards": pf_eng.forwards, "dc_forwards": dc_eng.forwards,
            "pf_chunks": pf_eng.prefill_chunks,
            "dc_prefill": dc_eng.prefill_launches,
            "dc_steps": dc_eng._steps,
            "shipped": pf_eng.blocks_shipped,
            "handoffs_out": pf_eng.handoffs_out,
            "handoff_ms": pf_eng.handoff_ms + dc_eng.handoff_ms}
    router = RequestRouter(block_size=8)
    router.upsert_replica("prefill:0", client=pf_client,
                          stats=pf_eng.stats())
    router.upsert_replica("decode:0", client=dc_client,
                          stats=dc_eng.stats())

    def refresh() -> None:
        router.upsert_replica("prefill:0", client=pf_client,
                              stats=pf_eng.stats())
        router.upsert_replica("decode:0", client=dc_client,
                              stats=dc_eng.stats())

    dis = _drive_routed_trace(router, prompts, new_tokens, arrivals,
                              refresh=refresh)

    col_floor = [col["latency_ms"][r] for r in floor_rids]
    dis_floor = [dis["wall_latency_ms"][r] for r in floor_rids]
    dc_steps = dc_eng._steps - warm["dc_steps"]
    out = {
        "metric": "disagg_bench",
        "disagg_floor_requests": n_floor,
        "disagg_burst_requests": n_burst,
        "disagg_burst_prompt_tokens": burst_len,
        "backend": jax.default_backend(),
        # THE isolation claim, in the machine-independent currency
        # (launches on the decode critical path): the colocated engine
        # interleaves one burst-chunk launch into a large fraction of
        # the floor's decode iterations; the split decode replica's
        # loop carries ZERO prefill launches — isolation by
        # construction, not a mitigation. On metal a 32x256-row chunk
        # launch is compute-bound and costs at least a (bytes-bound)
        # decode launch, so the interleave fraction IS the decode
        # latency tax (ROOFLINE §11); on XLA-CPU the same chunk launch
        # is artificially cheap next to a batched decode step, which is
        # why the wall numbers below understate the split.
        "disagg_colocated_prefill_chunks": col["prefill_chunks"],
        "disagg_colocated_steps": col["steps"],
        "disagg_colocated_iteration_prefill_fraction": round(
            col["prefill_chunks"] / col["steps"], 3) if col["steps"]
        else None,
        "disagg_decode_prefill_launches":
            dc_eng.prefill_launches - warm["dc_prefill"],
        "disagg_decode_steps": dc_steps,
        # Measured, not asserted: 0.0 whenever no handoff fell back to
        # colocated prefill on the decode replica (the HandoffError
        # path) — a run where fallbacks fired reports the real fraction
        # next to the launch count above instead of a constant.
        "disagg_decode_iteration_prefill_fraction": round(
            (dc_eng.prefill_launches - warm["dc_prefill"]) / dc_steps, 3)
        if dc_steps else None,
        "disagg_prefill_gang_chunks":
            pf_eng.prefill_chunks - warm["pf_chunks"],
        "disagg_decode_forwards": dc_eng.forwards - warm["dc_forwards"],
        # The handoff ledger: what moving the KV actually cost.
        "disagg_blocks_shipped": pf_eng.blocks_shipped - warm["shipped"],
        "disagg_handoffs": pf_eng.handoffs_out - warm["handoffs_out"],
        "disagg_handoff_ms_total": round(
            pf_eng.handoff_ms + dc_eng.handoff_ms - warm["handoff_ms"],
            2),
        # Wall latencies as measured on this backend (see sim note).
        "disagg_colocated_floor_p50_ms": round(pctl(col_floor, 0.50), 2),
        "disagg_colocated_floor_p99_ms": round(pctl(col_floor, 0.99), 2),
        "disagg_split_floor_p50_ms": round(pctl(dis_floor, 0.50), 2),
        "disagg_split_floor_p99_ms": round(pctl(dis_floor, 0.99), 2),
        "disagg_floor_p99_isolation_wall": round(
            pctl(col_floor, 0.99) / pctl(dis_floor, 0.99), 3)
        if pctl(dis_floor, 0.99) else None,
        "disagg_burst_p99_ms": round(
            pctl([dis["wall_latency_ms"][r] for r in burst_rids], 0.99),
            2),
        "disagg_numerics_ok": dis["tokens"] == col["tokens"],
    }
    if not on_tpu:
        out["disagg_sim_note"] = (
            "CPU simulation with INVERTED launch economics: on this "
            "backend a (1,32) chunk launch is compute-bound and cheap "
            "next to a batched (8,16) decode step, so the colocated "
            "engine's interleave tax — the thing disaggregation removes "
            "— barely registers in wall time, while the split gang "
            "pays real costs metal does not charge (two 'replicas' "
            "contending for one host CPU, a per-request dispatch "
            "thread, and host-RAM device round trips per handoff). "
            "disagg_floor_p99_isolation_wall on this host is therefore "
            "BELOW 1 and is explicitly NOT the claim. The claims that "
            "transfer: disagg_decode_prefill_launches == 0 vs the "
            "colocated engine's interleave fraction "
            "(disagg_colocated_iteration_prefill_fraction of decode "
            "iterations carry a chunk launch — on metal each costs >= "
            "a decode launch, ROOFLINE §11, so that fraction is the "
            "floor's latency tax), the launch split across the gangs, "
            "disagg_blocks_shipped with the handoff byte math, and "
            "disagg_numerics_ok (identical token streams, handoff "
            "included). Metal wall p99 rides the real-hardware debt "
            "list (ROADMAP)")
    return out


def run_kvtier_bench(*, n_conversations: int | None = None,
                     n_turns: int | None = None, seed: int = 0,
                     on_tpu: bool | None = None) -> dict:
    """KV-memory-hierarchy leg (tony_tpu.serve PR 16): multi-turn
    conversations against an engine with the host-offload tier armed
    (idle conversations PARK — their KV demotes to host RAM between
    turns and resumes through the atomic import path) vs the identical
    engine that recomputes every turn's history from scratch. Both
    engines see the SAME conversations: rounds of turn-requests, every
    conversation's turn-t prompt being its full accumulated history
    plus fresh user tokens (the chat-completion wire shape).

    The headline is turn-resume latency; the machine-independent claims
    are the prefill-ROW ledger — a resumed turn issues prefill rows
    ONLY for the uncovered suffix (``kvtier_covered_extent_prefill_rows
    == 0``: not one row recomputes history the parked record already
    holds), the park hit rate, and the demote/promote ledger. Token
    identity is gated: the parked engine's streams are bitwise the
    recompute engine's (the parity the kvtier tests pin row-by-row on
    logits). CPU wall numbers measure scheduling plus genuinely saved
    prefill compute (``kvtier_sim_note``)."""
    import numpy as np

    import flax.linen as nn

    from tony_tpu.models import get_model
    from tony_tpu.serve import Request, ServeEngine

    if on_tpu is None:
        on_tpu = jax.default_backend() not in ("cpu",)
    if n_conversations is None:
        n_conversations = 8
    if n_turns is None:
        n_turns = 3
    turn_tokens, max_new = 12, 6
    rng = np.random.RandomState(seed)
    model = get_model("llama-tiny", n_layers=2)
    toks0 = jnp.zeros((1, 16), jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(seed), toks0))["params"]
    params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        params)

    def build(tag: str, **kw) -> ServeEngine:
        return ServeEngine(model, params, ctx_max=128, block_size=8,
                           q_block=16, decode_buckets=(8,),
                           max_running=n_conversations,
                           tag=f"kvtier_bench_{tag}", **kw)

    parked = build("parked", host_blocks=512)
    plain = build("recompute")

    # Resume-start ledger: record where each resumed admission begins
    # its prefill so the covered-extent row count is computed EXACTLY
    # (measured rows minus the padded uncovered suffix == 0), not
    # inferred from a ratio.
    starts: dict = {}
    orig_resume = parked._try_resume

    def _spy(req, total):
        res = orig_resume(req, total)
        if res is not None:
            starts[req.rid] = res[0]
        return res

    parked._try_resume = _spy

    # Fixed per-turn geometry (turn_tokens user tokens, max_new
    # generated) keeps the jit-shape family identical across
    # conversations and rounds: ONE warm conversation driven through
    # all n_turns hits every prefill pad and decode bucket the
    # measured trace will, for both engines.
    def drive_round(eng, histories, fresh, conv_tags, t):
        reqs = []
        for i, hist in enumerate(histories):
            prompt = list(hist) + [int(x) for x in fresh[i]]
            kw = {}
            if conv_tags is not None:
                kw["conv"] = conv_tags[i]
            reqs.append((f"t{t}c{i}", prompt))
            eng.submit(Request(rid=f"t{t}c{i}", tokens=prompt,
                               max_new_tokens=max_new, **kw))
        t0 = time.perf_counter()
        done = {c.rid: c for c in eng.run()}
        wall = time.perf_counter() - t0
        out_hist = []
        for i, (rid, prompt) in enumerate(reqs):
            out_hist.append(prompt + list(done[rid].tokens))
        lats = [done[rid].latency_s * 1e3 for rid, _ in reqs]
        toks = {rid: list(done[rid].tokens) for rid, _ in reqs}
        return out_hist, lats, toks, wall

    def warm(eng, tag):
        hist = []
        w = np.random.RandomState(seed + 999)
        for t in range(n_turns):
            hists, _, _, _ = drive_round(
                eng, [hist], [w.randint(0, model.cfg.vocab, turn_tokens)],
                [f"warm-{tag}"] if tag == "parked" else None, f"w{t}")
            hist = hists[0]

    warm(parked, "parked")
    warm(plain, "plain")
    starts.clear()
    snap = {e: {"rows": e.prefill_rows, "launches": e.prefill_launches,
                "hits": e.park_hits, "lookups": e.park_lookups,
                "demoted": e.cache.demoted_total,
                "promoted": e.cache.promoted_total}
            for e in (parked, plain)}

    fresh = [[rng.randint(0, model.cfg.vocab, turn_tokens)
              for _ in range(n_conversations)] for _ in range(n_turns)]
    p_hist = [[] for _ in range(n_conversations)]
    r_hist = [[] for _ in range(n_conversations)]
    convs = [f"c{i}" for i in range(n_conversations)]
    rows_at_round, lat_parked, lat_plain = {}, [], []
    numerics_ok = True
    for t in range(n_turns):
        rows_at_round[t] = (parked.prefill_rows, plain.prefill_rows)
        p_hist, pl, ptoks, _ = drive_round(parked, p_hist, fresh[t],
                                           convs, t)
        r_hist, rl, rtoks, _ = drive_round(plain, r_hist, fresh[t],
                                           None, t)
        numerics_ok = numerics_ok and ptoks == rtoks
        if t > 0:                       # resume turns only
            lat_parked.extend(pl)
            lat_plain.extend(rl)

    def pctl(vals, p):
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(p * (len(vals) - 1) + 0.5))]

    # The covered-extent ledger: every resumed turn's measured rows
    # must equal the q_block-padded UNCOVERED suffix exactly.
    resumed = {rid: s for rid, s in starts.items()
               if not rid.startswith("t0")}
    expected_suffix_rows = 0
    for t in range(1, n_turns):
        for i in range(n_conversations):
            rid = f"t{t}c{i}"
            if rid not in resumed:
                continue
            prompt_len = len(p_hist[i]) - (n_turns - t) * (
                turn_tokens + max_new)
            t_real = prompt_len - resumed[rid]
            expected_suffix_rows += -(-t_real // parked.q_block) \
                * parked.q_block
    parked_resume_rows = parked.prefill_rows - rows_at_round[1][0]
    plain_resume_rows = plain.prefill_rows - rows_at_round[1][1]
    stats = parked.stats()
    out = {
        "metric": "kvtier_bench",
        "kvtier_conversations": n_conversations,
        "kvtier_turns": n_turns,
        "kvtier_turn_user_tokens": turn_tokens,
        "kvtier_turn_new_tokens": max_new,
        "backend": jax.default_backend(),
        # THE resume claim, in the machine-independent currency: a
        # resumed turn prefills the uncovered suffix ONLY — zero rows
        # recompute history the parked record covers. On metal each
        # elided row is prefill compute bought back at host<->device
        # copy prices (ROOFLINE §12); here the ledger is exact.
        "kvtier_park_hits": parked.park_hits - snap[parked]["hits"],
        "kvtier_park_lookups":
            parked.park_lookups - snap[parked]["lookups"],
        "kvtier_park_hit_rate": round(stats["park_hit_rate"], 3),
        "kvtier_resume_prefill_rows": parked_resume_rows,
        "kvtier_recompute_prefill_rows": plain_resume_rows,
        "kvtier_covered_extent_prefill_rows":
            parked_resume_rows - expected_suffix_rows,
        "kvtier_resume_row_fraction": round(
            parked_resume_rows / plain_resume_rows, 3)
        if plain_resume_rows else None,
        "kvtier_demotions":
            parked.cache.demoted_total - snap[parked]["demoted"],
        "kvtier_promotions":
            parked.cache.promoted_total - snap[parked]["promoted"],
        "kvtier_host_blocks_used": int(stats["host_blocks"]),
        "kvtier_parked_seqs": int(stats["parked_seqs"]),
        # Wall latencies over the resume turns (t >= 2), as measured.
        "kvtier_resume_p50_ms": round(pctl(lat_parked, 0.50), 2),
        "kvtier_resume_p99_ms": round(pctl(lat_parked, 0.99), 2),
        "kvtier_recompute_p50_ms": round(pctl(lat_plain, 0.50), 2),
        "kvtier_recompute_p99_ms": round(pctl(lat_plain, 0.99), 2),
        "kvtier_resume_speedup_p50_wall": round(
            pctl(lat_plain, 0.50) / pctl(lat_parked, 0.50), 3)
        if pctl(lat_parked, 0.50) else None,
        "kvtier_numerics_ok": numerics_ok,
    }
    parked.cache.close()
    if not on_tpu:
        out["kvtier_sim_note"] = (
            "CPU simulation: the wall speedup mixes genuinely saved "
            "prefill compute (XLA-CPU really does run the elided rows' "
            "flops) with scheduling noise, and the host tier's "
            "demote/promote 'copies' are host-RAM memcpys rather than "
            "PCIe/ICI transfers — so the wall numbers neither price "
            "the copy nor the HBM it frees. The claims that transfer: "
            "kvtier_covered_extent_prefill_rows == 0 (a resumed turn "
            "recomputes NOTHING the parked record covers), the "
            "resume-vs-recompute row ledger with the ROOFLINE §12 "
            "bytes-per-elided-flop math, the park hit rate, and "
            "kvtier_numerics_ok (bitwise identical streams). Metal "
            "wall latency rides the real-hardware debt list (ROADMAP)")
    return out


def run_coldstart_bench(*, seed: int = 0,
                        on_tpu: bool | None = None) -> dict:
    """Replica cold-start leg (tony_tpu.ckpt.aot PR 17): grant→first-
    token for three replica starts against the SAME workload — a COLD
    replica (empty AOT cache: every step program traces and compiles at
    warm time, populating the cache), a CACHE-HIT replica (same
    fingerprints: warm() deserializes persisted executables in
    milliseconds and the start executes ZERO fresh traces or compiles —
    counter-pinned), and a WARM-STANDBY replica (compiled ahead of the
    clock; its grant cost is one promote() RPC plus the first request).

    The wall split is broken out per start: engine build, warm (further
    split by the engine's own compile_ms vs deserialize_ms ledgers),
    and first-token. The machine-independent claims are the cache
    counters (hit start: ``fresh_compiles == 0`` AND the raw-jit memo
    stays EMPTY — nothing traced) and token identity: all three starts'
    streams are bitwise equal, logits included. XLA-CPU compile walls
    stand in for TPU compile walls (``coldstart_sim_note``)."""
    import shutil
    import tempfile

    import numpy as np

    import flax.linen as nn

    from tony_tpu.ckpt.aot import AOTCache
    from tony_tpu.models import get_model
    from tony_tpu.serve import Request, ServeEngine

    if on_tpu is None:
        on_tpu = jax.default_backend() not in ("cpu",)
    model = get_model("llama-tiny", n_layers=2)
    toks0 = jnp.zeros((1, 16), jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(seed), toks0))["params"]
    params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        params)
    rng = np.random.RandomState(seed)
    prompts = [rng.randint(1, 200, size=n).tolist() for n in (5, 3, 9)]
    max_new = 6
    root = tempfile.mkdtemp(prefix="tony_coldstart_bench_")

    def build(tag: str, **kw) -> ServeEngine:
        # One decode bucket + prompts under one q_block: the FULL step
        # family is two programs — (4, 16) decode/verify and (1, 16)
        # monolithic prefill — so warm(prefill_pads=(16,)) provably
        # covers every shape the drive launches.
        return ServeEngine(model, params, ctx_max=128, block_size=8,
                           q_block=16, decode_buckets=(4,),
                           max_running=4, keep_logits=True,
                           aot_cache=AOTCache(root),
                           tag=f"coldstart_bench_{tag}", **kw)

    def first_token_ms(eng) -> float:
        t0 = time.perf_counter()
        eng.submit(Request(rid="probe", tokens=list(prompts[0]),
                           max_new_tokens=1))
        done = list(eng.run())
        assert len(done) == 1 and len(done[0].tokens) == 1
        return 1e3 * (time.perf_counter() - t0)

    def drive(eng) -> dict:
        for i, p in enumerate(prompts):
            eng.submit(Request(rid=i, tokens=list(p),
                               max_new_tokens=max_new))
        return {c.rid: c for c in eng.run()}

    def start(tag: str, **kw) -> tuple:
        """One replica start: build + warm + first token, timed."""
        t0 = time.perf_counter()
        eng = build(tag, **kw)
        build_ms = 1e3 * (time.perf_counter() - t0)
        t0 = time.perf_counter()
        warmed = eng.warm(prefill_pads=(16,))
        warm_ms = 1e3 * (time.perf_counter() - t0)
        ft_ms = first_token_ms(eng)
        return eng, {
            f"coldstart_{tag}_build_ms": round(build_ms, 2),
            f"coldstart_{tag}_warm_ms": round(warm_ms, 2),
            f"coldstart_{tag}_warm_programs": warmed,
            f"coldstart_{tag}_compile_ms": round(eng.compile_ms, 2),
            f"coldstart_{tag}_deserialize_ms":
                round(eng.deserialize_ms, 2),
            f"coldstart_{tag}_first_token_ms": round(ft_ms, 2),
            f"coldstart_{tag}_grant_to_first_token_ms":
                round(build_ms + warm_ms + ft_ms, 2),
            f"coldstart_{tag}_fresh_compiles": eng.fresh_compiles,
            f"coldstart_{tag}_aot_hits": eng.aot_hits,
            f"coldstart_{tag}_aot_misses": eng.aot_misses,
        }

    out = {"metric": "coldstart_bench",
           "backend": jax.default_backend(),
           "coldstart_max_new_tokens": max_new}

    # Leg 1 — COLD: empty cache, warm pays the full trace+compile wall
    # AND persists every executable for the fleet.
    cold, row = start("cold")
    out.update(row)
    ref = drive(cold)

    # Leg 2 — CACHE-HIT: a fresh replica on the populated cache. The
    # acceptance pin: zero fresh traces or compiles across the ENTIRE
    # start-and-serve — and the raw-jit memo must stay empty (had
    # anything traced, it would live there).
    hit, row = start("hit")
    out.update(row)
    got_hit = drive(hit)
    out["coldstart_hit_zero_fresh_compiles"] = (
        hit.fresh_compiles == 0 and len(hit._fns) == 0)

    # Leg 3 — WARM-STANDBY: compiled ahead of the clock (untimed); the
    # grant is one promote() flip plus the first request.
    standby = build("standby", warm_standby=True)
    standby.warm(prefill_pads=(16,))
    t0 = time.perf_counter()
    assert standby.promote()
    promote_ms = 1e3 * (time.perf_counter() - t0)
    ft_ms = first_token_ms(standby)
    out["coldstart_standby_promote_ms"] = round(promote_ms, 4)
    out["coldstart_standby_first_token_ms"] = round(ft_ms, 2)
    out["coldstart_standby_grant_to_first_token_ms"] = round(
        promote_ms + ft_ms, 2)
    out["coldstart_standby_fresh_compiles"] = standby.fresh_compiles
    got_standby = drive(standby)

    # Token identity across all three starts — the cache may cost a
    # compile, never a wrong program.
    numerics_ok = True
    for got in (got_hit, got_standby):
        numerics_ok = numerics_ok and sorted(got) == sorted(ref)
        for rid in ref:
            numerics_ok = (numerics_ok
                           and got[rid].tokens == ref[rid].tokens
                           and all(np.array_equal(a, b) for a, b in
                                   zip(got[rid].logits, ref[rid].logits)))
    out["coldstart_numerics_ok"] = numerics_ok
    cold_wall = out["coldstart_cold_grant_to_first_token_ms"]
    hit_wall = out["coldstart_hit_grant_to_first_token_ms"]
    sb_wall = out["coldstart_standby_grant_to_first_token_ms"]
    out["coldstart_hit_speedup_wall"] = (
        round(cold_wall / hit_wall, 2) if hit_wall else None)
    out["coldstart_standby_speedup_wall"] = (
        round(cold_wall / sb_wall, 2) if sb_wall else None)
    shutil.rmtree(root, ignore_errors=True)
    if not on_tpu:
        out["coldstart_sim_note"] = (
            "CPU simulation: XLA-CPU compiles the tiny 2-layer step in "
            "tens of milliseconds where XLA-TPU spends seconds-to-"
            "minutes on a real model, so the wall split UNDERSTATES "
            "the cold-start win; params are handed over in memory, so "
            "the checkpoint-restore segment of a real grant (priced by "
            "the ckpt bench, ROOFLINE §7) is absent from every leg. "
            "The claims that transfer: the cache state machine (cold "
            "populates, hit deserializes), "
            "coldstart_hit_zero_fresh_compiles (a cache-hit start "
            "traces and compiles NOTHING — the counter pin), the "
            "standby grant collapsing to promote + first request, and "
            "coldstart_numerics_ok (bitwise identical streams, logits "
            "included). ROOFLINE §13 prices the metal version")
    return out


def run_resize_bench(*, hidden: int = 1024, steps: int = 24,
                     resize_at: int = 12,
                     directory: str | None = None,
                     on_tpu: bool | None = None) -> dict:
    """Elastic-resize leg (tony_tpu.am.resize, PR 19): what a drain →
    commit → re-gang → restore cycle costs the training timeline, and
    whether it costs the MODEL anything. Two runs over the same batch
    schedule:

    * **undisturbed reference** — ``steps`` optimizer steps straight
      through;
    * **elastic run** — the same schedule interrupted at ``resize_at``
      by the resize lifecycle's data plane: a synchronous drain-commit
      (the train loop's EXIT_DRAINED contract — save + wait so the
      manifest is durable before the worker reports drained), then an
      elastic restore of the committed step (the re-gang survivor's
      first act on the new topology), then the remaining steps from the
      restored state.

    The headline is ``resize_overhead_s`` (elastic wall − undisturbed
    wall) decomposed into ``drain_commit_s`` + ``restore_s``; ROOFLINE
    §15 prices the same walls against checkpoint size and host I/O. The
    machine-independent claim is ``resize_numerics_ok``: the elastic
    run's final state is BITWISE the undisturbed run's — a resize that
    moves the loss curve is a restart, not a resize (tests/
    test_elastic.py pins the example-id stream and multi-preemption
    composition on top)."""
    import shutil
    import tempfile
    from pathlib import Path

    import numpy as np
    import optax

    from tony_tpu import ckpt as ckpt_mod
    from tony_tpu import parallel as par
    from tony_tpu import train as tr
    from tony_tpu.models import get_model

    if on_tpu is None:
        on_tpu = jax.default_backend() not in ("cpu",)
    mesh = par.make_mesh(fsdp=1)
    batch = 8
    model = get_model("mnist-mlp", hidden=hidden)
    kx, ky, kr = jax.random.split(jax.random.PRNGKey(0), 3)
    xs = jax.random.normal(kx, (steps, batch, 784), jnp.float32)
    ys = jax.random.randint(ky, (steps, batch), 0, 10)
    state0 = tr.create_train_state(model, optax.sgd(0.1, momentum=0.9),
                                   xs[0], kr)
    step = tr.make_train_step(mesh=mesh, donate=False)
    _ = step(state0, {"x": xs[0], "y": ys[0]})      # warm the compile

    def run_steps(state, lo: int, hi: int):
        for i in range(lo, hi):
            state, _ = step(state, {"x": xs[i], "y": ys[i]})
        jax.block_until_ready(state.params)
        return state

    root = Path(directory) if directory else Path(tempfile.mkdtemp(
        prefix="tony-resize-bench-"))
    try:
        t0 = time.perf_counter()
        ref = run_steps(state0, 0, steps)
        undisturbed_s = time.perf_counter() - t0

        ck = ckpt_mod.AsyncCheckpointer(root / "resize", keep=2)
        t0 = time.perf_counter()
        state = run_steps(state0, 0, resize_at)
        t1 = time.perf_counter()
        ck.save(state, step=resize_at, block=True)  # the drain commit
        t2 = time.perf_counter()
        abstract = jax.tree.map(
            lambda a: np.zeros(a.shape, a.dtype)
            if hasattr(a, "shape") else a, jax.device_get(state))
        restored = ckpt_mod.restore_pytree(root / "resize", abstract,
                                           mesh=mesh)
        t3 = time.perf_counter()
        final = run_steps(restored, resize_at, steps)
        elastic_s = time.perf_counter() - t0
        nbytes = ck.stats["nbytes"]
        ck.close()

        exact = all(
            np.array_equal(np.asarray(jax.device_get(a)),
                           np.asarray(jax.device_get(b)))
            for a, b in zip(jax.tree.leaves(final), jax.tree.leaves(ref))
            if hasattr(b, "shape"))
    finally:
        if not directory:
            shutil.rmtree(root, ignore_errors=True)
    out = {
        "metric": "resize_bench",
        "resize_steps": steps,
        "resize_at": resize_at,
        "resize_state_mb": round(nbytes / (1024 * 1024), 3),
        "resize_undisturbed_s": round(undisturbed_s, 6),
        "resize_elastic_s": round(elastic_s, 6),
        "resize_overhead_s": round(elastic_s - undisturbed_s, 6),
        "resize_drain_commit_s": round(t2 - t1, 6),
        "resize_restore_s": round(t3 - t2, 6),
        "resize_numerics_ok": bool(exact),
        "backend": jax.default_backend(),
    }
    if not on_tpu:
        out["resize_sim_note"] = (
            "CPU simulation: the walls price the lifecycle's DATA plane "
            "(drain-commit + elastic restore) in one process — the "
            "container re-grant and gang re-negotiation between them "
            "are scheduler walls the MiniPod e2e measures, and tmpfs "
            "I/O understates a real host's commit/restore cost "
            "(ROOFLINE §15 prices both). The claim that transfers: "
            "resize_numerics_ok — the interrupted run's final state is "
            "bitwise the undisturbed run's")
    return out


def run_qos_bench(*, n_victim: int | None = None,
                  n_aggressor: int | None = None, seed: int = 0,
                  on_tpu: bool | None = None) -> dict:
    """Multi-tenant QoS leg (tony_tpu.serve.qos, PR 18) on the shared
    Poisson protocol with an AGGRESSOR-BURST phase: a victim tenant's
    steady decode floor (short prompts, real generation lengths — the
    BENCH_r12 workload) absorbs a tight cluster of long-prompt
    admissions from an aggressor tenant one third into the trace — the
    noisy-neighbor regime weighted-fair budgets exist for. Three
    configurations run the victim's requests on the SAME arrival
    schedule:

    * **unloaded reference** — the victim floor alone on a plain
      engine: the bitwise baseline for the victim's token streams;
    * **budgets off** (``qos=None``) — tenant tags ride the requests
      but nothing enforces them: the burst's admissions take running
      slots and pool blocks first-come-first-served and the victim
      queues behind them;
    * **budgets on** — ``QosPolicy(victim:3, aggressor:1)`` over the
      same pool: the admission scan DEFERS aggressor requests past
      their weighted-fair block share (skip-over; per-tenant FIFO
      preserved) and the victim's requests admit past them.

    The headline is victim p99 with vs without budgets under the same
    burst. The machine-independent claims: the deferral ledger
    (``qos_deferrals`` > 0 budgeted, == 0 unbudgeted, rejections 0 in
    both — deferral is back-pressure on the aggressor, never a drop or
    a victim penalty) and ``qos_numerics_ok`` (the victim's token
    streams in BOTH loaded configurations bitwise-match the unloaded
    reference, and the full trace matches across budgets on/off — QoS
    moves WHEN work admits, never WHAT it computes; tests/test_qos.py
    pins the per-token logits too). CPU wall numbers measure
    scheduling on a shared host (``qos_sim_note``)."""
    import numpy as np

    import flax.linen as nn

    from tony_tpu.models import get_model
    from tony_tpu.serve import Request, ServeEngine
    from tony_tpu.serve.qos import QosPolicy

    if on_tpu is None:
        on_tpu = jax.default_backend() not in ("cpu",)
    if n_victim is None:
        n_victim = 16
    if n_aggressor is None:
        n_aggressor = 8
    burst_len = 48                      # 6 pool blocks per admission
    rng = np.random.RandomState(seed)
    model = get_model("llama-tiny", n_layers=2)
    toks0 = jnp.zeros((1, 16), jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(seed), toks0))["params"]
    params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        params)

    def build(tag: str, **kw) -> ServeEngine:
        # 64-block pool (ctx 64 / block 8 x 8 running): the burst's 6
        # blocks per admission make the aggressor's 1/4 fair share (16
        # blocks) genuinely binding mid-trace.
        return ServeEngine(model, params, ctx_max=64, block_size=8,
                           q_block=16, decode_buckets=(8,), max_running=8,
                           tag=f"qos_bench_{tag}", **kw)

    # The workload: the BENCH_r12/r15 floor (short prompts, real
    # generation lengths) tagged "victim", plus a burst of long prompts
    # tagged "aggressor" landing in a tight cluster one third in.
    victim_prompts = [list(rng.randint(0, model.cfg.vocab,
                                       4 + int(rng.randint(9))))
                      for _ in range(n_victim)]
    victim_new = [int(rng.randint(10, 17)) for _ in range(n_victim)]
    agg_prompts = [list(rng.randint(0, model.cfg.vocab, burst_len))
                   for _ in range(n_aggressor)]
    # Long generations too: each burst admission HOLDS its 6+ blocks
    # for many decode steps, so later aggressor admissions genuinely
    # exceed the fair share mid-trace instead of draining before the
    # budget binds.
    agg_new = [int(rng.randint(8, 13)) for _ in range(n_aggressor)]

    # BENCH_r12..r17 calibration protocol: arrival gaps scaled off a
    # measured engine step so the floor overlaps itself on any backend.
    probe = build("probe")
    probe.submit(Request(rid="probe", tokens=victim_prompts[0],
                         max_new_tokens=4))
    probe.run()
    t0 = time.perf_counter()
    probe.submit(Request(rid="probe2", tokens=victim_prompts[0],
                         max_new_tokens=4))
    steps0 = probe._steps
    probe.run()
    step_s = (time.perf_counter() - t0) / max(1, probe._steps - steps0)
    victim_arrivals = np.cumsum(rng.exponential(1.5 * step_s, n_victim))
    t_burst = float(victim_arrivals[n_victim // 3])
    agg_arrivals = t_burst + 0.1 * step_s * np.arange(n_aggressor)

    # One merged trace sorted by arrival; tenant membership remembered
    # by rid so the percentile split and the bitwise victim gate
    # survive the sort (victims keep their relative order, so victim j
    # of the merged trace IS request j of the unloaded reference).
    merged = sorted(
        [(a, p, n, "victim") for a, p, n in zip(victim_arrivals,
                                                victim_prompts,
                                                victim_new)]
        + [(a, p, n, "aggressor") for a, p, n in zip(agg_arrivals,
                                                     agg_prompts,
                                                     agg_new)],
        key=lambda t: t[0])
    arrivals = [t[0] for t in merged]
    prompts = [t[1] for t in merged]
    new_tokens = [t[2] for t in merged]
    tenants = [t[3] for t in merged]
    victim_rids = [f"r{i}" for i, t in enumerate(merged)
                   if t[3] == "victim"]
    agg_rids = [f"r{i}" for i, t in enumerate(merged)
                if t[3] == "aggressor"]

    def pctl(vals, p):
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(p * (len(vals) - 1) + 0.5))]

    # -- unloaded reference (victim floor alone) -------------------------
    ref_eng = build("reference")
    ref = _drive_serve_trace(ref_eng, victim_prompts, victim_new,
                             list(victim_arrivals))

    # -- budgets off: tags ride, nothing enforces ------------------------
    off_eng = build("budgets_off")
    off = _drive_serve_trace(off_eng, prompts, new_tokens, arrivals,
                             tenants=tenants)

    # -- budgets on: weighted-fair admission -----------------------------
    pol = QosPolicy(classes={"victim": 3.0, "aggressor": 1.0})
    on_eng = build("budgets_on", qos=pol)
    on = _drive_serve_trace(on_eng, prompts, new_tokens, arrivals,
                            tenants=tenants)
    on_stats = on_eng.stats()

    vict_ok = all(
        off["tokens"][rid] == ref["tokens"][f"r{j}"]
        and on["tokens"][rid] == ref["tokens"][f"r{j}"]
        for j, rid in enumerate(victim_rids))
    off_v = [off["latency_ms"][r] for r in victim_rids]
    on_v = [on["latency_ms"][r] for r in victim_rids]
    ref_v = [ref["latency_ms"][r] for r in ref["latency_ms"]]
    out = {
        "metric": "qos_bench",
        "qos_victim_requests": n_victim,
        "qos_aggressor_requests": n_aggressor,
        "qos_aggressor_prompt_tokens": burst_len,
        "qos_pool_blocks": on_eng.cache.n_blocks,
        "qos_weights": {"victim": 3.0, "aggressor": 1.0},
        # The fair-share math the admission scan enforces mid-burst
        # (both tenants active): weight/(sum of active weights) x pool.
        "qos_aggressor_budget_blocks": pol.budget(
            "aggressor", on_eng.cache.n_blocks, ("victim", "aggressor")),
        "qos_victim_budget_blocks": pol.budget(
            "victim", on_eng.cache.n_blocks, ("victim", "aggressor")),
        "backend": jax.default_backend(),
        # The deferral ledger — back-pressure lands on the aggressor
        # as waiting, never as a drop (rejections need a queue cap,
        # unset here) and never on the victim.
        "qos_deferrals_budgeted": on_eng.qos_deferrals,
        "qos_deferrals_unbudgeted": off_eng.qos_deferrals,
        "qos_rejections_budgeted": on_eng.admission_rejections,
        "qos_rejections_unbudgeted": off_eng.admission_rejections,
        # The heartbeat view of the budgeted run: per-tenant lifetime
        # completions from the SAME stats() payload the session and
        # the history plane consume.
        "qos_tenant_completed": {
            t: d["completed"] for t, d in on_stats["tenants"].items()},
        # Wall latencies as measured on this backend (see sim note).
        "qos_victim_p50_ms_unloaded": round(pctl(ref_v, 0.50), 2),
        "qos_victim_p99_ms_unloaded": round(pctl(ref_v, 0.99), 2),
        "qos_victim_p50_ms_unbudgeted": round(pctl(off_v, 0.50), 2),
        "qos_victim_p99_ms_unbudgeted": round(pctl(off_v, 0.99), 2),
        "qos_victim_p50_ms_budgeted": round(pctl(on_v, 0.50), 2),
        "qos_victim_p99_ms_budgeted": round(pctl(on_v, 0.99), 2),
        "qos_victim_p99_isolation_wall": round(
            pctl(off_v, 0.99) / pctl(on_v, 0.99), 3)
        if pctl(on_v, 0.99) else None,
        # What fairness costs the aggressor: its p99 under deferral vs
        # first-come-first-served (the flip side of the victim's win).
        "qos_aggressor_p99_ms_unbudgeted": round(
            pctl([off["latency_ms"][r] for r in agg_rids], 0.99), 2),
        "qos_aggressor_p99_ms_budgeted": round(
            pctl([on["latency_ms"][r] for r in agg_rids], 0.99), 2),
        "qos_numerics_ok": vict_ok and on["tokens"] == off["tokens"],
    }
    if not on_tpu:
        out["qos_sim_note"] = (
            "CPU simulation: wall latencies measure engine scheduling "
            "on a shared host, and the burst's 48-token prefill "
            "launches are artificially cheap next to batched decode "
            "steps on XLA-CPU (the BENCH_r12 executable-alternation "
            "artifact), so qos_victim_p99_isolation_wall understates "
            "what the same deferral buys on metal, where each "
            "aggressor admission costs compute-bound prefill launches "
            "on the victim's critical path (ROOFLINE §14 prices the "
            "fair-share math). The claims that transfer: the deferral "
            "ledger (budgets defer the aggressor, zero deferrals "
            "without budgets, zero drops in both), the per-tenant "
            "completion ledger from the heartbeat schema, and "
            "qos_numerics_ok (victim streams bitwise equal to the "
            "unloaded engine with budgets on or off). Metal wall p99 "
            "rides the real-hardware debt list (ROADMAP)")
    return out
