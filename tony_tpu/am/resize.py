"""Elastic gang resize: the drain → re-gang → restore state machine.

On a worker preemption / lost heartbeat (or an operator ``tony resize
N``), the AM stops answering churn with the most expensive recovery it
has (the full gang restart of ``tony.am.retry-count``) and instead
walks the gang through

    RUNNING → DRAINING → RE-GANG → RESTORING → RUNNING

* **DRAINING** — survivors are told to stop at the next step boundary
  (the drain directive rides the heartbeat *response*; the executor
  materializes it as a drain file the train loop polls). Each survivor
  commits model + data cursor through the PR 3 atomic manifest and
  exits ``EXIT_DRAINED`` — a clean, non-failing terminal.
* **RE-GANG** — the AM rewrites the gang's instance count, re-saves the
  job config, and relaunches at the new host count through the normal
  launch machinery; healthy containers' allocations/workdirs are reused
  (``jax.distributed`` cannot re-negotiate membership in-process, so
  the worker *processes* restart regardless — the savings is the
  container setup, not the process).
* **RESTORING** — the relaunched gang restores elastically: the PR 3
  manifest maps onto the changed mesh, the PR 4 cursor continues the
  global example stream element-identically, and the PR 17 AOT cache's
  mesh-keyed fingerprint makes a previously-seen geometry pay zero
  recompile.

This module is the *pure* half: :class:`ResizeController` owns phase
order, per-phase deadlines, wall-clock accounting, and the degrade
verdict, while the AM injects the live predicates (``poll``) and phase
entry actions (``enter``). The controller is tick-driven from the AM
monitor loop — it never blocks, so a wedged phase can only *time out*
(degrading to the full gang restart), never hang. Unit tests drive
``tick()`` with a fake clock and pin exactly that.

Failures are typed: :class:`ResizeError` carries the phase and a
``retryable`` flag. A drain that cannot complete is NOT retryable as a
resize (the surviving checkpoint may predate the drain request — only
the gang restart's restore-from-last-commit is safe); re-gang/restore
failures are retryable (the next preemption or operator verb may try
again) but still degrade this resize to the restart path.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, Dict, Mapping, Optional

__all__ = ["ResizePhase", "ResizeError", "ResizeSpec", "ResizeTimeouts",
           "ResizeResult", "ResizeController"]


class ResizePhase(Enum):
    DRAINING = "DRAINING"
    REGANG = "RE-GANG"
    RESTORING = "RESTORING"


class ResizeError(RuntimeError):
    """A resize phase failed. ``retryable`` says whether a LATER resize
    attempt is sound (re-gang/restore hiccups) or whether only the full
    gang restart is (drain never finished — the last commit may predate
    the drain request). Either way THIS resize degrades."""

    def __init__(self, phase: ResizePhase, message: str, *,
                 retryable: bool):
        super().__init__(f"{phase.value}: {message}")
        self.phase = phase
        self.retryable = retryable


@dataclass(frozen=True)
class ResizeSpec:
    """One resize's intent: what triggered it and the topology change."""
    trigger: str                 # "preempted" | "lost" | "operator"
    job_type: str
    old_workers: int
    new_workers: int


@dataclass(frozen=True)
class ResizeTimeouts:
    """Per-phase wall budgets (seconds). Every phase is bounded — the
    never-hang guarantee is these three numbers plus the tick loop."""
    drain_s: float = 60.0
    regang_s: float = 120.0
    restore_s: float = 120.0

    def budget(self, phase: ResizePhase) -> float:
        return {ResizePhase.DRAINING: self.drain_s,
                ResizePhase.REGANG: self.regang_s,
                ResizePhase.RESTORING: self.restore_s}[phase]


@dataclass
class ResizeResult:
    """Terminal verdict of one resize attempt. ``degraded`` means the
    caller must fall back to the full gang restart; ``phase_walls``
    carries per-phase wall seconds for the RESIZE history records."""
    ok: bool
    spec: ResizeSpec
    degraded: bool = False
    failed_phase: Optional[ResizePhase] = None
    retryable: bool = True
    reason: str = ""
    phase_walls: Dict[str, float] = field(default_factory=dict)


# Signature of the per-phase observer: (spec, phase, wall_s, ok, detail).
PhaseObserver = Callable[[ResizeSpec, ResizePhase, float, bool, str], None]


class ResizeController:
    """Tick-driven resize machine.

    ``poll`` maps each phase to a zero-arg completion predicate (True =
    phase done); ``enter`` optionally maps a phase to a zero-arg entry
    action fired once when the phase begins. Both run on the caller's
    thread (the AM monitor loop). A predicate/entry raising is treated
    as that phase failing (wrapped in :class:`ResizeError` unless it
    already is one).

    Drive with :meth:`start` then :meth:`tick` until a
    :class:`ResizeResult` comes back; ``on_phase`` (when given) observes
    every phase completion/failure — the AM points it at the RESIZE
    event emitter so recovery timelines land in the history plane.
    """

    _ORDER = (ResizePhase.DRAINING, ResizePhase.REGANG,
              ResizePhase.RESTORING)

    def __init__(self, *,
                 poll: Mapping[ResizePhase, Callable[[], bool]],
                 enter: Optional[Mapping[ResizePhase,
                                         Callable[[], None]]] = None,
                 timeouts: Optional[ResizeTimeouts] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_phase: Optional[PhaseObserver] = None):
        missing = [p.value for p in self._ORDER if p not in poll]
        if missing:
            raise ValueError(f"resize poll map is missing phases "
                             f"{missing}")
        self._poll = dict(poll)
        self._enter = dict(enter or {})
        self.timeouts = timeouts or ResizeTimeouts()
        self._clock = clock
        self._on_phase = on_phase
        self.spec: Optional[ResizeSpec] = None
        self.phase: Optional[ResizePhase] = None
        self._phase_t0 = 0.0
        self._walls: Dict[str, float] = {}

    @property
    def active(self) -> bool:
        return self.spec is not None

    def start(self, spec: ResizeSpec) -> None:
        if self.active:
            raise ResizeError(self.phase or ResizePhase.DRAINING,
                              "a resize is already in flight",
                              retryable=True)
        if spec.new_workers < 1:
            raise ValueError(
                f"resize to {spec.new_workers} workers: a gang needs at "
                f"least 1")
        self.spec = spec
        self._walls = {}
        self._begin(self._ORDER[0])

    def _begin(self, phase: ResizePhase) -> None:
        self.phase = phase
        self._phase_t0 = self._clock()
        entry = self._enter.get(phase)
        if entry is not None:
            entry()

    def _observe(self, phase: ResizePhase, wall: float, ok: bool,
                 detail: str) -> None:
        if self._on_phase is not None:
            self._on_phase(self.spec, phase, wall, ok, detail)

    def _fail(self, err: ResizeError) -> ResizeResult:
        spec, phase = self.spec, self.phase
        wall = self._clock() - self._phase_t0
        self._walls[phase.value] = wall
        self._observe(phase, wall, False, str(err))
        result = ResizeResult(ok=False, spec=spec, degraded=True,
                              failed_phase=phase,
                              retryable=err.retryable, reason=str(err),
                              phase_walls=dict(self._walls))
        self.spec = None
        self.phase = None
        return result

    def tick(self) -> Optional[ResizeResult]:
        """Advance the machine one observation; returns the terminal
        :class:`ResizeResult` when the resize completes or degrades,
        ``None`` while a phase is still in flight. Bounded: a phase
        whose predicate never turns true fails at its deadline."""
        if not self.active:
            return None
        phase = self.phase
        try:
            done = bool(self._poll[phase]())
        except ResizeError as e:
            return self._fail(e)
        except Exception as e:  # predicate blew up: that phase failed
            return self._fail(ResizeError(
                phase, f"phase check raised {type(e).__name__}: {e}",
                retryable=phase is not ResizePhase.DRAINING))
        now = self._clock()
        if not done:
            if now - self._phase_t0 > self.timeouts.budget(phase):
                return self._fail(ResizeError(
                    phase,
                    f"timed out after {self.timeouts.budget(phase):.1f}s",
                    retryable=phase is not ResizePhase.DRAINING))
            return None
        wall = now - self._phase_t0
        self._walls[phase.value] = wall
        self._observe(phase, wall, True, "")
        idx = self._ORDER.index(phase)
        if idx + 1 < len(self._ORDER):
            try:
                self._begin(self._ORDER[idx + 1])
            except ResizeError as e:
                return self._fail(e)
            except Exception as e:
                return self._fail(ResizeError(
                    self._ORDER[idx + 1],
                    f"phase entry raised {type(e).__name__}: {e}",
                    retryable=True))
            return None
        result = ResizeResult(ok=True, spec=self.spec,
                              phase_walls=dict(self._walls))
        self.spec = None
        self.phase = None
        return result

    def abandon(self, reason: str) -> Optional[ResizeResult]:
        """Force-degrade an in-flight resize (e.g. the AM is shutting
        down): terminal result now, never a dangling phase."""
        if not self.active:
            return None
        return self._fail(ResizeError(self.phase, f"abandoned: {reason}",
                                      retryable=True))
