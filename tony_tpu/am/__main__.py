"""``python -m tony_tpu.am`` — standalone AM process (reference:
``TonyApplicationMaster.main``, launched in the AM container by the RM on the
client's behalf — SURVEY.md §3.1)."""

import argparse
import signal
import sys

from tony_tpu.util import restore_site_dirs

restore_site_dirs()   # -S entry: see tony_tpu.util.ENV_SITE_DIRS

from tony_tpu.am import ApplicationMaster
from tony_tpu.conf import TonyConfig


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tony-am")
    p.add_argument("--conf", required=True, help="serialized job config")
    p.add_argument("--app-id", required=True)
    p.add_argument("--job-dir", required=True)
    p.add_argument("--host", default="127.0.0.1",
                   help="address executors use to reach the AM RPC")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args(argv)
    conf = TonyConfig.load(args.conf)
    am = ApplicationMaster(conf, app_id=args.app_id, job_dir=args.job_dir,
                           host=args.host, quiet=not args.verbose)
    # Graceful SIGTERM (client kill fallback): drain through the AM's normal
    # teardown instead of dying mid-loop and orphaning executor groups.
    signal.signal(signal.SIGTERM,
                  lambda _sig, _frm: am.request_stop("AM received SIGTERM"))
    try:
        return am.run()
    except Exception as e:  # noqa: BLE001 — AM-internal failure, not job's
        from tony_tpu import constants
        print(f"[tony-am] internal error: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)
        return constants.EXIT_AM_ERROR


if __name__ == "__main__":
    sys.exit(main())
