"""ApplicationMaster: the scheduler brain (layer L4).

Mirrors ``com.linkedin.tony.TonyApplicationMaster`` (upstream ``tony-core/src/
main/java/com/linkedin/tony/TonyApplicationMaster.java`` ≈1,500 LoC,
unverified — SURVEY.md §0, call stacks §3.1/§3.3). Responsibilities carried
over, re-mapped from YARN to the :mod:`tony_tpu.scheduler` substrate:

* translate per-jobtype config into container launches (gang allocation);
* serve the control-plane RPC (register / cluster-spec / heartbeat /
  result / metrics) to executors;
* the monitor loop: heartbeat-expiry → LOST, completed-container handling,
  preemption re-request (``tony.container.preemption.max-retries``), gang
  allocation timeout, application timeout;
* success policy via :class:`~tony_tpu.session.TonySession`;
* AM-attempt gang restart (``tony.am.retry-count``) — `jax.distributed` is
  unforgiving about world membership (SURVEY.md §7 hard part #1), so a retry
  tears down the WHOLE gang and relaunches with ``attempt_id + 1``;
* lifecycle event emission to the jhist log (SURVEY.md §3.5).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path
from typing import Dict, Optional

from tony_tpu import conf as conf_mod
from tony_tpu import constants
from tony_tpu.am import resize as resize_mod
from tony_tpu.conf import TonyConfig
from tony_tpu.events import EventHandler
from tony_tpu.rpc import ENV_JOB_TOKEN, ApplicationRpcHandler, RpcServer
from tony_tpu.scheduler import (Container, ContainerLaunch,
                                ContainerScheduler, LocalProcessScheduler)
from tony_tpu.session import JobStatus, TaskStatus, TonySession

AM_ADDRESS_FILE = "am.address"
AM_TOKEN_FILE = "am.token"
FINAL_STATUS_FILE = "final-status.json"
_TICK_S = 0.05


class ApplicationMaster:
    """One AM process/thread: owns the RPC server, the scheduler client, the
    session, and the monitor loop."""

    def __init__(self, conf: TonyConfig, app_id: str, job_dir: str | Path,
                 scheduler: Optional[ContainerScheduler] = None,
                 host: str = "127.0.0.1", quiet: bool = True):
        self.conf = conf
        self.app_id = app_id
        # Resolve: executors run with a different cwd, so every path shipped
        # to them (conf, src) must be absolute.
        self.job_dir = Path(job_dir).resolve()
        self.job_dir.mkdir(parents=True, exist_ok=True)
        if scheduler is None:
            # Config-selected backend (tpu-vm) or fall through to local.
            from tony_tpu.scheduler import scheduler_from_conf
            scheduler = scheduler_from_conf(conf, self.job_dir, host)
        if scheduler is None:
            # Local substrate: enforce chip asks against what this host
            # actually has (reference: GpuDiscoverer feeding the AM's
            # resource accounting) whenever any job type requests tpus.
            total_tpus = 0
            if any(conf.get_int(conf_mod.tpus_key(jt), 0) > 0
                   for jt in conf.job_types()):
                total_tpus = conf.get_int(conf_mod.SCHEDULER_TOTAL_TPUS, 0)
                if total_tpus <= 0:
                    from tony_tpu.discovery import discover_tpus
                    total_tpus = discover_tpus(use_jax=True).num_chips
                if total_tpus <= 0:
                    # 0 would mean "unlimited" to the scheduler — the
                    # opposite of what an unsatisfiable ask deserves.
                    raise ValueError(
                        "tony.<jobtype>.tpus requested but no TPU chips "
                        "discovered on this host; set "
                        f"{conf_mod.SCHEDULER_TOTAL_TPUS} to override")
            scheduler = LocalProcessScheduler(
                self.job_dir, host=host, conf=conf, total_tpus=total_tpus)
        self.scheduler = scheduler
        self.host = host
        self.quiet = quiet
        self.token: Optional[str] = None
        self.credentials: Optional[Dict[str, str]] = None
        self.cred_provider = None
        if conf.get_bool(conf_mod.SECURITY_ENABLED, False):
            from tony_tpu import security
            self.cred_provider = security.provider_for(conf)
            # Client-staged credentials win (acquire-at-submit); acquiring
            # here covers AMs launched without a client (MiniPod/tests) and
            # keeps every hop working from the same map.
            self.credentials = security.read_credentials(self.job_dir)
            if self.credentials is None:
                self.credentials = self.cred_provider.acquire(
                    conf, self.job_dir)
                security.write_credentials(self.job_dir, self.credentials)
            self.token = self.credentials.get("token")
            if not self.token:
                # The pre-SPI behavior ALWAYS authenticated the RPC
                # surface when security was on; a provider that ships
                # only external credentials must not silently downgrade.
                raise ValueError(
                    f"{conf_mod.SECURITY_ENABLED} is true but credential "
                    f"provider {type(self.cred_provider).__name__} "
                    f"supplied no 'token' entry to authenticate RPC")
            # Back-compat surface older clients poll for.
            token_path = self.job_dir / AM_TOKEN_FILE
            token_path.write_text(self.token)
            token_path.chmod(0o600)
        from tony_tpu.runtime import get_framework
        self.framework = get_framework(
            conf.get(conf_mod.APPLICATION_FRAMEWORK, "jax"))
        self.session: Optional[TonySession] = None
        self.server: Optional[RpcServer] = None
        self.handler: Optional[ApplicationRpcHandler] = None
        self.events: Optional[EventHandler] = None
        self._containers: Dict[str, Container] = {}   # task_id -> live container
        self.final_status = JobStatus.FAILED
        self.final_message = ""
        self.history_dir: Optional[Path] = None       # set in run()
        self._stop_reason: Optional[str] = None       # set by request_stop
        # Elastic resize (tony_tpu.am.resize): one controller at a time,
        # ticked from the monitor loop; it survives across attempts (the
        # drain ends one attempt, re-gang/restore run in the next).
        self._resize: Optional[resize_mod.ResizeController] = None
        self._resize_count = 0
        self._resize_relaunch = False
        self._operator_resize: Optional[int] = None   # set from RPC thread

    def _log(self, msg: str) -> None:
        if not self.quiet:
            print(f"[tony-am {self.app_id}] {msg}", file=sys.stderr, flush=True)

    def _maybe_refresh_credentials(self) -> None:
        """Periodic provider renewal (reference: delegation-token renewal).
        Providers renew EXTERNAL credentials (ticket/cred files user code
        reads); the in-flight RPC token is job-lifetime — see
        tony_tpu.security. Interval 0 (default) disables the hook."""
        if self.cred_provider is None or self.credentials is None:
            return
        from tony_tpu import security
        interval_s = self.conf.get_int(
            security.CREDENTIAL_REFRESH_INTERVAL_MS, 0) / 1e3
        if interval_s <= 0:
            return
        now = time.monotonic()
        if now < getattr(self, "_next_cred_refresh", 0.0):
            return
        self._next_cred_refresh = now + interval_s
        try:
            renewed = self.cred_provider.refresh(
                self.conf, self.job_dir, dict(self.credentials))
        except Exception as e:  # noqa: BLE001 — provider is plugin code
            self._log(f"credential refresh failed (kept current): {e}")
            return
        if renewed is not None:
            self.credentials = renewed
            security.write_credentials(self.job_dir, renewed)
            self._log("credentials refreshed")

    def request_stop(self, reason: str) -> None:
        """Graceful external stop (SIGTERM from the client's kill fallback).
        Signal-handler safe: only sets a flag — no locks — and the monitor
        loop applies it (KILLED → normal teardown: containers reaped, events
        finalized, final status written)."""
        self._stop_reason = reason

    # -- container plumbing ------------------------------------------------
    def _launch_task(self, session: TonySession, job_type: str,
                     index: int) -> None:
        req = self.conf.container_request(job_type)
        env = {
            constants.ENV_JOB_NAME: job_type,
            constants.ENV_TASK_INDEX: str(index),
            constants.ENV_TASK_NUM: str(session.num_tasks()),
            # The REACHABLE address (matches the am.address file), not
            # RpcServer.address which maps a 0.0.0.0 bind to loopback and
            # would strand remote executors.
            constants.ENV_AM_ADDRESS: f"{self.host}:{self.server.port}",  # type: ignore[union-attr]
            constants.ENV_APP_ID: self.app_id,
            constants.ENV_ATTEMPT_ID: str(session.attempt_id),
            constants.ENV_CONF_PATH: str(self.job_dir / constants.TONY_JOB_JSON),
        }
        src = self.job_dir / "src"
        if src.is_dir():
            env[constants.ENV_SRC_DIR] = str(src)
        res = self.job_dir / "resources"
        if res.is_dir():
            env[constants.ENV_RESOURCES_DIR] = str(res)
        venv = self.conf.get(conf_mod.PYTHON_VENV)
        if venv and Path(venv).exists():
            # Resolve against the AM's cwd (= the client's, which wrote the
            # conf): executors run elsewhere and a relative path would
            # silently localize nothing.
            env[constants.ENV_VENV] = str(Path(venv).resolve())
        if self.credentials is not None and self.cred_provider is not None:
            # The provider decides what ships into containers (reference:
            # tokens packed into every ContainerLaunchContext).
            env.update(self.cred_provider.executor_env(self.credentials))
        elif self.token:
            env[ENV_JOB_TOKEN] = self.token
        container = self.scheduler.launch(ContainerLaunch(
            job_type=job_type, index=index, env=env,
            memory_mb=req.memory_mb, vcores=req.vcores, tpus=req.tpus))
        task = session.task(job_type, index)
        with session.lock:
            task.container_id = container.container_id
            if not task.status.is_terminal:
                task.status = TaskStatus.ALLOCATED
            task.touch()
        self._containers[task.task_id] = container
        self._log(f"launched {task.task_id} in {container.container_id}")

    def _try_launch(self, session: TonySession, job_type: str,
                    index: int) -> None:
        """Launch, converting substrate failures (unsatisfiable resource
        ask, staging error on the ssh substrate) into a task failure the
        success policy sees — not an AM crash (reference: the RM rejecting
        an ask surfaces as a failed container, never kills the AM)."""
        try:
            self._launch_task(session, job_type, index)
        except Exception as e:  # noqa: BLE001 — substrate errors vary
            self._log(f"launch of {job_type}:{index} failed: {e}")
            session.on_task_result(
                job_type, index, constants.EXIT_AM_ERROR,
                f"container launch failed: {e}")

    def _stop_task_containers(self, session: TonySession) -> None:
        for task in session.tasks():
            c = self._containers.get(task.task_id)
            if c is not None and c.is_running:
                self.scheduler.stop_container(c)

    # -- elastic resize ----------------------------------------------------
    def _resize_job_type(self) -> str:
        return self.conf.get(conf_mod.RESIZE_JOB_TYPE) or constants.WORKER

    def _resize_enabled(self, job_type: str) -> bool:
        return (self.conf.get_bool(conf_mod.RESIZE_ENABLED, False)
                and job_type == self._resize_job_type())

    def _resize_timeouts(self) -> resize_mod.ResizeTimeouts:
        return resize_mod.ResizeTimeouts(
            drain_s=self.conf.get_int(
                conf_mod.RESIZE_DRAIN_TIMEOUT_MS, 60000) / 1e3,
            regang_s=self.conf.get_int(
                conf_mod.RESIZE_REGANG_TIMEOUT_MS, 120000) / 1e3,
            restore_s=self.conf.get_int(
                conf_mod.RESIZE_RESTORE_TIMEOUT_MS, 120000) / 1e3)

    def _request_operator_resize(self, n: int) -> None:
        """RPC-thread half of ``tony resize N``: record the ask only —
        the monitor loop owns every session/scheduler mutation, so the
        RPC thread must not trigger the resize itself."""
        self._operator_resize = int(n)

    def _emit_resize_phase(self, spec: resize_mod.ResizeSpec,
                           phase: resize_mod.ResizePhase, wall_s: float,
                           ok: bool, detail: str) -> None:
        self._log(f"resize {phase.value}: "
                  f"{'done' if ok else 'FAILED'} in {wall_s:.2f}s"
                  + (f" ({detail})" if detail else ""))
        if self.events is not None:
            self.events.resize(phase.value, spec.trigger, spec.job_type,
                               spec.old_workers, spec.new_workers,
                               wall_s, ok, detail)

    def _regang_poll(self) -> bool:
        """RE-GANG completes when the NEW attempt's gang barrier seals
        (the draining attempt's session is excluded by its drain flag)."""
        s = self.session
        return (s is not None and not s.draining
                and self.handler is not None
                and self.handler._all_registered_fired
                and s.all_registered())

    def _restore_poll(self) -> bool:
        """RESTORING completes when every tracked task of the resized
        jobtype is RUNNING and heartbeating on the new topology (restore
        CORRECTNESS — element-identical stream, mesh-mapped params — is
        the ckpt/data planes' pinned contract, not re-checked here)."""
        s = self.session
        if s is None or s.draining:
            return False
        jt = self._resize_job_type()
        gang = [t for t in s.tasks() if t.job_type == jt and t.tracked]
        return bool(gang) and all(
            t.status == TaskStatus.RUNNING and t.last_heartbeat is not None
            for t in gang)

    def _trigger_resize(self, session: TonySession, trigger: str,
                        job_type: str, new_workers: int) -> bool:
        """Begin a resize (drain phase starts immediately). False means
        this churn must fall back to the pre-elastic recovery path
        (resize disabled, wrong jobtype, or the resize budget is spent);
        True with a resize already in flight folds the churn into it."""
        if not self._resize_enabled(job_type):
            return False
        if self._resize is not None and self._resize.active:
            return True
        max_resizes = self.conf.get_int(conf_mod.RESIZE_MAX_RESIZES, 8)
        if self._resize_count >= max_resizes:
            self._log(f"resize budget exhausted "
                      f"({self._resize_count}/{max_resizes}); "
                      f"falling back to gang restart")
            return False
        floor = max(1, self.conf.get_int(conf_mod.RESIZE_MIN_WORKERS, 1))
        target = max(int(new_workers), floor)
        spec = resize_mod.ResizeSpec(
            trigger=trigger, job_type=job_type,
            old_workers=self.conf.instances(job_type),
            new_workers=target)
        controller = resize_mod.ResizeController(
            poll={
                resize_mod.ResizePhase.DRAINING:
                    lambda: (self.session is not None
                             and self.session.drain_complete(job_type)),
                resize_mod.ResizePhase.REGANG: self._regang_poll,
                resize_mod.ResizePhase.RESTORING: self._restore_poll,
            },
            enter={resize_mod.ResizePhase.DRAINING: session.request_drain},
            timeouts=self._resize_timeouts(),
            on_phase=self._emit_resize_phase)
        self._resize = controller
        self._resize_count += 1
        self._log(f"resize #{self._resize_count} ({trigger}): "
                  f"{spec.old_workers} -> {target} {job_type}(s); draining")
        controller.start(spec)
        return True

    def _divert_to_resize(self, session: TonySession, task,
                          trigger: str, reason: str) -> bool:
        """Route one task's churn (preemption / lost heartbeat) into the
        resize machine instead of the same-index retry or the fail-fast
        LOST verdict. The churned task goes terminal WITHOUT failing the
        job (mark_scaled_down); survivors drain at the next heartbeat."""
        if not self._resize_enabled(task.job_type):
            return False
        if self._resize is None or not self._resize.active:
            live = [t for t in session.tasks()
                    if t.job_type == task.job_type and t.tracked
                    and not t.status.is_terminal and t is not task]
            if not self._trigger_resize(session, trigger, task.job_type,
                                        len(live)):
                return False
        session.mark_scaled_down(task, reason)
        c = self._containers.get(task.task_id)
        if c is not None and c.is_running:
            self.scheduler.stop_container(c)
        return True

    def _tick_resize(self, session: TonySession) -> None:
        """One monitor-loop observation of the in-flight resize. Ends the
        DRAINING attempt when the commit is durable (run() then re-gangs
        at the new size), and on a terminal verdict either celebrates or
        degrades the job to the full-gang-restart path."""
        c = self._resize
        if c is None or not c.active:
            return
        result = c.tick()
        if result is None:
            if session.draining \
                    and c.phase is not resize_mod.ResizePhase.DRAINING:
                # Drain committed: end this attempt so run() can apply
                # the new topology and relaunch (re-gang).
                self._resize_relaunch = True
            return
        self._resize = None
        spec = result.spec
        if result.ok:
            walls = ", ".join(f"{k} {v:.2f}s"
                              for k, v in result.phase_walls.items())
            self._log(f"resize complete: {spec.old_workers} -> "
                      f"{spec.new_workers} {spec.job_type}(s) ({walls})")
            return
        # Degrade: never a hang, never a torn checkpoint — the gang
        # restart's restore-from-last-commit owns recovery from here.
        self._log(f"resize degraded ({result.reason}); full gang restart")
        session.clear_drain()
        with session.lock:
            if session.job_status == JobStatus.RUNNING:
                session.job_status = JobStatus.FAILED
                session.final_message = f"resize degraded: {result.reason}"

    # -- monitor-loop checks ----------------------------------------------
    def _check_heartbeats(self, session: TonySession) -> None:
        interval_s = self.conf.get_int(
            conf_mod.TASK_HEARTBEAT_INTERVAL_MS, 1000) / 1e3
        max_missed = self.conf.get_int(conf_mod.TASK_MAX_MISSED_HEARTBEATS, 25)
        expiry = interval_s * max_missed
        now = time.monotonic()
        # Before the gang barrier, non-registration is the gang timeout's
        # job; after it, a relaunched (preempted) executor that freezes
        # before registering has no other watchdog, so ALLOCATED tasks are
        # covered too (touch() at launch seeds their clock).
        barrier_passed = (self.handler is not None
                          and self.handler._all_registered_fired)
        watched = (TaskStatus.REGISTERED, TaskStatus.RUNNING) if not \
            barrier_passed else (TaskStatus.ALLOCATED, TaskStatus.REGISTERED,
                                 TaskStatus.RUNNING)
        for task in session.tasks():
            if task.status in watched \
                    and task.last_heartbeat \
                    and now - task.last_heartbeat > expiry:
                if self._divert_to_resize(
                        session, task, "lost",
                        f"missed {max_missed} heartbeats; "
                        f"elastic resize in place of LOST"):
                    self._log(f"task {task.task_id} missed {max_missed} "
                              f"heartbeats -> elastic resize")
                    continue
                self._log(f"task {task.task_id} missed {max_missed} "
                          f"heartbeats -> LOST")
                session.on_task_lost(
                    task, f"missed {max_missed} heartbeats "
                          f"({expiry:.1f}s without contact)")
                c = self._containers.get(task.task_id)
                if c is not None and c.is_running:
                    self.scheduler.stop_container(c)

    def _handle_completed_containers(self, session: TonySession) -> None:
        max_preempt = self.conf.get_int(conf_mod.PREEMPTION_MAX_RETRIES, 3)
        for c in self.scheduler.poll_completed():
            task = session.task_by_container(c.container_id)
            if task is None:
                continue
            live = self._containers.get(task.task_id)
            if live is not None and live.container_id == c.container_id:
                del self._containers[task.task_id]
            if task.status.is_terminal:
                continue
            if c.exit_code == constants.EXIT_PREEMPTED:
                if self._divert_to_resize(
                        session, task, "preempted",
                        "preempted; elastic resize in place of retry"):
                    self._log(f"{task.task_id} preempted -> elastic resize")
                    continue
                task.preemption_retries += 1
                if task.preemption_retries <= max_preempt:
                    self._log(f"{task.task_id} preempted "
                              f"(retry {task.preemption_retries}/{max_preempt})"
                              f" -> re-requesting container")
                    with session.lock:
                        task.host = task.port = None
                        task.status = TaskStatus.REQUESTED
                    self._try_launch(session, task.job_type, task.index)
                else:
                    session.on_task_result(
                        task.job_type, task.index, constants.EXIT_PREEMPTED,
                        f"preempted {task.preemption_retries} times "
                        f"(max {max_preempt})")
            else:
                # Executor died without a result RPC (crash, OOM-kill).
                session.on_task_result(
                    task.job_type, task.index,
                    c.exit_code if c.exit_code else constants.EXIT_FAILURE,
                    f"executor exited with {c.exit_code} without reporting")

    def _log_history_events(self, session: TonySession) -> None:
        """Append each task's latest stats-file window to the jhist log
        (tony_tpu.events SERVE_WINDOW / TRAIN_STEP) — the history
        plane's ONLY collection hook: the payload is the task's already-
        normalized heartbeat dict verbatim (no second bookkeeping path),
        de-duplicated per task so an idle tick appends nothing. A dict
        carrying a train step counter (the train stats writer's schema)
        logs as TRAIN_STEP; everything else is a serve window."""
        if self.events is None:
            return
        if not hasattr(self, "_history_window_sig"):
            self._history_window_sig: Dict[str, str] = {}
        for t in session.tasks():
            m = t.serve_metrics
            if not m or t.status.is_terminal:
                continue
            sig = json.dumps(m, sort_keys=True, default=str)
            if self._history_window_sig.get(t.task_id) == sig:
                continue
            self._history_window_sig[t.task_id] = sig
            if "step" in m and "qps" not in m:
                self.events.train_step(
                    t.job_type, t.index, step=int(m.get("step", 0)),
                    step_time_s=float(m.get("step_time_s", 0.0)),
                    collective_bytes=float(m.get("collective_bytes",
                                                 0.0)),
                    mfu=float(m.get("mfu", 0.0)))
            else:
                self.events.serve_window(t.job_type, t.index, m)

    def _autoscale_serve(self, session: TonySession) -> None:
        """Heartbeat-driven replica scaling for every serving jobtype
        (tony_tpu.serve): feed the replicas' piggybacked qps/p99/queue-
        depth into the pure :func:`tony_tpu.serve.scaling.decide` policy
        and apply the delta — launch an ELASTIC task on scale-up, retire
        the newest elastic replica on scale-down (the conf-declared
        floor is untouchable). Autoscale is off unless the conf raises
        ``tony.serve.replicas.max`` above the static instance count.
        Only runs after the gang barrier: the initial gang must seal its
        cluster spec before membership gets elastic.

        Per-JOBTYPE since the disaggregated split (the first
        heterogeneous-gang consumer): a job's prefill and decode gangs
        are separate serve-role jobtypes, each with its own policy
        instance (floor = its own conf instance count), cooldown clock,
        and samples — a prefill burst scales the prefill gang, the
        decode floor stays put."""
        if self.handler is None or not self.handler._all_registered_fired:
            return
        serve_jts = session.serve_job_types()
        if not serve_jts:
            return
        from tony_tpu.serve import scaling    # jax-free

        if not hasattr(self, "_serve_policy"):
            self._serve_policy: Dict[str, object] = {}
            self._serve_scale_last: Dict[str, Optional[float]] = {}
        for jt in serve_jts:
            if jt not in self._serve_policy:
                # job_type + fleet_floors: on a split fleet the global
                # replicas.max is a FLEET ceiling apportioned across
                # the gangs (scaling.apportion_fleet_max), overridable
                # per gang via tony.serve.replicas.max.<jobtype>.
                self._serve_policy[jt] = scaling.ScalingPolicy.from_conf(
                    self.conf, self.conf.instances(jt), job_type=jt,
                    fleet_floors={j: self.conf.instances(j)
                                  for j in serve_jts})
                self._serve_scale_last[jt] = None
            policy = self._serve_policy[jt]
            # Partition the live gang: warm STANDBYS (heartbeating
            # warm_standby — the cold-start plane's compiled-and-idle
            # pool, tony_tpu.ckpt.aot) are held capacity, not serving
            # replicas. The load policy sees ONLY the active set; the
            # pool has its own target (decide_warm) below.
            live = [t for t in session.tasks()
                    if t.job_type == jt and not t.status.is_terminal]
            warm = [t for t in live
                    if t.serve_metrics.get("warm_standby")]
            active = [t for t in live
                      if not t.serve_metrics.get("warm_standby")]
            # Floor REPAIR runs even when autoscale is off: `tony serve`
            # disables fail-fast on the promise that a crashed replica
            # gets replaced, so below-floor recovery must not hide
            # behind the max>min autoscale arming.
            warm_target = self._serve_warm_target(jt)
            if not policy.enabled and len(active) >= policy.min_replicas \
                    and warm_target <= 0:
                continue
            now = time.monotonic()
            samples = [s for s in session.serve_samples(jt)
                       if not s.get("warm_standby")]
            delta = scaling.decide(policy, len(active), samples, now=now,
                                   last_action=self._serve_scale_last[jt])
            if delta and self.events is not None:
                # The SELF-VERIFYING record (before the applied action
                # updates the cooldown clock): decide()'s complete input
                # next to the delta, so scaling.replay_decisions over
                # the finished log reproduces this exact verdict.
                self.events.scale_decision(
                    jt, delta, len(active), samples, now,
                    self._serve_scale_last[jt],
                    dataclasses.asdict(policy))
            if delta > 0:
                # The grant names the prefix store (when conf declares
                # one): the fresh replica warms its prefix tier from
                # disk instead of recomputing hot stems, so a scale-up
                # replica is useful from its first request.
                store = self.conf.get(
                    conf_mod.SERVE_PREFIX_STORE, "") or ""
                store_note = f", prefix store {store}" if store else ""
                for _ in range(delta):
                    # A warm standby PROMOTES in place of a cold grant:
                    # one RPC flips it active — executables and prefix
                    # stems already hot. Cold launch is the fallback
                    # (no pool, or the promote RPC failed).
                    if warm and self._promote_standby(jt, warm, active):
                        continue
                    task = session.add_task(jt)
                    self._log(f"serve scale-up -> launching elastic "
                              f"replica {task.task_id} "
                              f"({len(active) + 1} active{store_note})")
                    self._try_launch(session, jt, task.index)
                self._serve_scale_last[jt] = now
            elif delta < 0:
                victims = sorted((t for t in active if t.elastic),
                                 key=lambda t: t.index, reverse=True)
                if victims:
                    victim = victims[0]
                    self._log(f"serve scale-down -> retiring elastic "
                              f"replica {victim.task_id} "
                              f"({len(active) - 1} active)")
                    session.mark_scaled_down(
                        victim, "replica scale-down (load below floor)")
                    c = self._containers.get(victim.task_id)
                    if c is not None and c.is_running:
                        self.scheduler.stop_container(c)
                    self._serve_scale_last[jt] = now
            # Warm-pool backfill AFTER the load verdict applied: grants
            # above the configured instance count self-identify as
            # standbys (replica.main), so a backfill launch comes up
            # compiled-and-idle; over-target pools (ceiling shrank, or
            # a promotion left a retiring active) drain newest-first.
            warm_delta = scaling.decide_warm(
                policy, warm_target, len(active), len(warm))
            if warm_delta > 0:
                for _ in range(warm_delta):
                    task = session.add_task(jt)
                    self._log(f"serve warm-pool -> launching standby "
                              f"replica {task.task_id} "
                              f"({len(warm) + 1}/{warm_target} warm)")
                    self._try_launch(session, jt, task.index)
            elif warm_delta < 0:
                pool = sorted((t for t in warm if t.elastic),
                              key=lambda t: t.index, reverse=True)
                for victim in pool[:-warm_delta]:
                    self._log(f"serve warm-pool -> retiring standby "
                              f"replica {victim.task_id}")
                    session.mark_scaled_down(
                        victim, "warm-standby pool over target")
                    c = self._containers.get(victim.task_id)
                    if c is not None and c.is_running:
                        self.scheduler.stop_container(c)

    def _serve_warm_target(self, job_type: str) -> int:
        """Configured warm-standby pool size for one serve jobtype —
        the per-gang ``tony.serve.warm-standby.<jobtype>`` override,
        else the global key, else 0 (pool off)."""
        v = self.conf.get(conf_mod.serve_warm_standby_key(job_type))
        if v is None:
            v = self.conf.get(conf_mod.SERVE_WARM_STANDBY)
        try:
            return int(v or 0)
        except (TypeError, ValueError):
            return 0

    def _promote_standby(self, job_type: str, warm: list,
                         active: list) -> bool:
        """Flip one warm standby active over its promote RPC (oldest
        first — it has donated stems longest). On success the task
        moves from ``warm`` to ``active`` in place so a multi-step
        delta keeps promoting; on RPC failure the standby stays pooled
        (its next heartbeat still says warm) and the caller falls back
        to a cold grant."""
        from tony_tpu.rpc import RpcClient, RpcError

        task = sorted(warm, key=lambda t: t.index)[0]
        port = task.serve_metrics.get("rpc_port")
        if not task.host or not port:
            return False
        try:
            with RpcClient(f"{task.host}:{int(port)}",
                           timeout=5.0) as client:
                client.call("promote")
        except (OSError, ValueError, RpcError) as e:
            self._log(f"serve scale-up -> promote RPC to "
                      f"{task.task_id} failed ({e}); cold-granting")
            return False
        # Reflect the promotion NOW (the replica republished stats, but
        # that lands on the next heartbeat): the session's view flips
        # with it so serve_endpoints routes the promoted replica this
        # tick.
        task.serve_metrics = dict(task.serve_metrics,
                                  warm_standby=0.0)
        warm.remove(task)
        active.append(task)
        self._log(f"serve scale-up -> promoted warm standby "
                  f"{task.task_id} ({len(active)} active, "
                  f"{len(warm)} warm)")
        return True

    def _tick_publication(self, session: TonySession) -> None:
        """Continuous weight publication (tony_tpu.publish /
        serve.swap): watch for a new published manifest and roll the
        serve fleet onto it, ONE replica at a time.

        Target discovery is two-source: the train gang's heartbeats
        carry the publication they staged (``task.published`` — the
        colocated train+serve job needs no extra wiring), and a
        ``tony.publish.follow`` job additionally polls the pointer file
        directly (throttled to ~1s — a follower fleet has no train
        tasks to hear it from). A new target emits ONE PUBLISH event
        and arms the :class:`~tony_tpu.serve.swap.FleetSwapController`;
        each tick then asks the controller who (if anyone) to swap —
        warm standbys first, then actives by index — down-marks that
        replica in place (``swapping=1.0``, the `_promote_standby`
        idiom, so serve_endpoints carries the retire signal THIS tick)
        and fires the ``swap`` RPC on a named daemon thread: the
        monitor loop never blocks on a restore. Each attempt's outcome
        lands as one SWAP event; a failure cools the controller down
        before the next try, and a wedged RPC is reaped at the
        configured timeout."""
        if self.handler is None or not self.handler._all_registered_fired:
            return
        serve_jts = session.serve_job_types()
        if not serve_jts:
            return
        from tony_tpu.serve.swap import FleetSwapController

        if not hasattr(self, "_swap_ctl"):
            self._swap_ctl = FleetSwapController(
                timeout_s=self.conf.get_int(
                    conf_mod.PUBLISH_SWAP_TIMEOUT_MS, 120000) / 1e3)
            self._pub_poll_t = 0.0
        ctl = self._swap_ctl
        best: Optional[tuple] = None
        for t in session.tasks():
            pub = getattr(t, "published", None)
            if pub and (best is None or pub["version"] > best[0]):
                best = (pub["version"], pub["step"])
        if self.conf.get_bool(conf_mod.PUBLISH_FOLLOW, False):
            now = time.monotonic()
            if now - self._pub_poll_t >= 1.0:
                self._pub_poll_t = now
                ckpt_dir = (self.conf.get(conf_mod.SERVE_CKPT_DIR)
                            or self.conf.get(conf_mod.CKPT_DIR))
                if ckpt_dir:
                    from tony_tpu.publish import latest_publication
                    rec = latest_publication(ckpt_dir)
                    if rec and (best is None or rec["version"] > best[0]):
                        best = (rec["version"], rec["step"])
        if best is not None and ctl.set_target(*best):
            self._log(f"publication v{best[0]} (step {best[1]}) -> "
                      f"rolling fleet swap")
            if self.events is not None:
                self.events.publish(best[0], best[1])
        if ctl.target is None:
            return
        wedged = ctl.check_timeout()
        if wedged is not None:
            self._log(f"swap of {wedged[0]}:{wedged[1]} timed out after "
                      f"{ctl.timeout_s:.0f}s")
            if self.events is not None:
                self.events.swap(wedged[0], wedged[1], 0, ctl.target[0],
                                 ctl.target[1], ctl.timeout_s, False,
                                 "swap RPC timed out")
        fleet = []
        by_id: Dict[tuple, object] = {}
        for t in session.tasks():
            m = t.serve_metrics
            if t.job_type not in serve_jts or t.status.is_terminal \
                    or not t.host or not m.get("rpc_port"):
                continue
            rid = (t.job_type, t.index)
            by_id[rid] = t
            fleet.append({"id": rid,
                          "version": int(m.get("weight_version", 0) or 0),
                          "standby": bool(m.get("warm_standby")),
                          "index": t.index})
        rid = ctl.next_replica(fleet)
        if rid is None:
            return
        task = by_id[rid]
        to_version, to_step = ctl.target
        from_version = int(task.serve_metrics.get("weight_version", 0)
                           or 0)
        addr = f"{task.host}:{int(task.serve_metrics['rpc_port'])}"
        # Down-mark in place: the router's next endpoints poll retires
        # this replica for the window; the replica's own post-swap
        # stats republish (swapping back to 0) revives it.
        task.serve_metrics = dict(task.serve_metrics, swapping=1.0)
        ctl.begin(rid)
        self._log(f"swap {task.task_id} v{from_version} -> v{to_version} "
                  f"(step {to_step})")

        def attempt() -> None:
            from tony_tpu.rpc import RpcClient, RpcError

            t0 = time.monotonic()
            ok, detail = True, ""
            try:
                with RpcClient(addr, timeout=ctl.timeout_s) as client:
                    client.call("swap", version=to_version, step=to_step)
            except (OSError, ValueError, RpcError) as e:
                ok, detail = False, str(e)
            ctl.finish(rid, ok)
            if self.events is not None:
                self.events.swap(rid[0], rid[1], from_version, to_version,
                                 to_step, time.monotonic() - t0, ok,
                                 detail)
            self._log(f"swap {task.task_id} -> v{to_version} "
                      + ("ok" if ok else f"FAILED ({detail})"))

        threading.Thread(target=attempt, daemon=True,
                         name=f"tony-swap-{task.task_id}").start()

    def _collect_traces_later(self, session: TonySession,
                              delay_s: float) -> None:
        """Wait for the executors' profiler endpoints to arrive (they're
        pushed after user-process launch, i.e. after the gang barrier),
        let the workload settle for ``delay_s``, then capture one trace
        per rank into ``<history>/traces/<app_id>/``."""
        from tony_tpu import profiler

        deadline = time.monotonic() + 120.0
        endpoints: Dict[str, str] = {}
        while time.monotonic() < deadline and not session.is_done():
            endpoints = profiler.endpoints_from_callback_info(
                session.task_callback_info)
            if endpoints:
                break
            time.sleep(0.25)
        if not endpoints:
            self._log("trace collection: no profiler endpoints appeared")
            return
        time.sleep(delay_s)
        if session.is_done():
            return
        # Re-read after the settle sleep: ranks whose executors pushed
        # their endpoint later than the first one (slow import, another
        # host) must not be excluded from the synchronized session.
        endpoints = profiler.endpoints_from_callback_info(
            session.task_callback_info) or endpoints
        duration_ms = self.conf.get_int(
            "tony.task.profiler.collect-duration-ms", 2000)
        assert self.history_dir is not None
        profiler.collect_traces(
            endpoints, self.history_dir, self.app_id,
            duration_ms=duration_ms,
            log=lambda *a, **k: self._log(" ".join(str(x) for x in a)))

    # -- one attempt -------------------------------------------------------
    def run_attempt(self, attempt_id: int) -> JobStatus:
        conf = self.conf
        session = TonySession(conf, self.app_id, attempt_id=attempt_id)
        self.session = session
        am_adapter = self.framework.am_adapter()
        am_adapter.validate_and_update_config(conf)
        am_adapter.set_session(session)
        if self.handler is None:
            self.handler = ApplicationRpcHandler(session)
        else:
            self.handler.reset(session)
        handler = self.handler

        def on_all_registered() -> None:
            am_adapter.on_all_registered()
            handler.callback_info.update(am_adapter.callback_info())
            # submit → all-RUNNING latency (BASELINE.md secondary metric):
            # the client ships its submit wall-clock in TONY_SUBMIT_TS.
            latency = None
            submit_ts = os.environ.get(constants.ENV_SUBMIT_TS)
            if submit_ts:
                try:
                    latency = time.time() - float(submit_ts)
                except ValueError:
                    pass
            session.all_running_latency_s = latency
            self._log("gang barrier passed: all tasks registered"
                      + (f" ({latency:.2f}s after submit)" if latency else ""))
            if self.events is not None:
                self.events.all_running(session.attempt_id, latency)
            # AM-side automatic trace collection (SURVEY.md §5.1): one
            # capture from every rank's profiler endpoint, N seconds after
            # the endpoints appear, into the history dir next to the jhist.
            collect_after = conf.get("tony.task.profiler.collect-after-s")
            if collect_after is not None and self.history_dir is not None:
                threading.Thread(
                    target=self._collect_traces_later,
                    args=(session, float(collect_after)),
                    daemon=True, name="trace-collect").start()

        handler.on_all_registered = on_all_registered
        handler.on_callback_info = am_adapter.receive_task_callback_info
        if conf.get_bool(conf_mod.RESIZE_ENABLED, False):
            handler.on_resize = self._request_operator_resize
        if self.events is not None:
            handler.on_registered = (
                lambda jt, i: self.events.task_started(
                    jt, i, session.task(jt, i).host or ""))
            handler.on_metrics = (
                lambda jt, i, m: self.events.task_metrics(jt, i, m))
        if self.server is None:
            self.server = RpcServer(handler, host="0.0.0.0",
                                    token=self.token).start()
            # Advertise the reachable address, not the bind-all one.
            (self.job_dir / AM_ADDRESS_FILE).write_text(
                f"{self.host}:{self.server.port}")
        if self.events is not None:
            self.events.application_inited(attempt_id, session.num_tasks())

        self._containers.clear()
        start = time.monotonic()
        gang_timeout_s = conf.get_int(conf_mod.AM_GANG_TIMEOUT_MS, 120000) / 1e3
        app_timeout_s = conf.get_int(conf_mod.APPLICATION_TIMEOUT, 0) / 1e3
        pending = [(jt, i) for jt in conf.job_types()
                   for i in range(conf.instances(jt))]
        launch_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="launch")
        try:
            while True:
                # Launch whatever the adapter allows (Horovod gates workers
                # on its driver being up — ``canStartTask``). Launches run
                # CONCURRENTLY: on the ssh substrate each launch pays
                # staging + connection latency, and a serial loop makes the
                # submit→all-running latency O(gang size) (SURVEY.md §7
                # hard part #4). The pool is joined before the tick
                # continues so completed-container/heartbeat checks never
                # race a half-launched task.
                still_pending = []
                launching = []
                for jt, i in pending:
                    if am_adapter.can_start_task(jt, i):
                        launching.append(launch_pool.submit(
                            self._try_launch, session, jt, i))
                    else:
                        still_pending.append((jt, i))
                for f in launching:
                    f.result()
                pending = still_pending

                self._handle_completed_containers(session)
                self._check_heartbeats(session)
                if self._operator_resize is not None:
                    n, self._operator_resize = self._operator_resize, None
                    jt = self._resize_job_type()
                    if not self._trigger_resize(session, "operator", jt, n):
                        self._log(f"operator resize to {n} refused")
                self._tick_resize(session)
                self._log_history_events(session)
                self._autoscale_serve(session)
                self._tick_publication(session)
                self._maybe_refresh_credentials()

                if self._stop_reason is not None:
                    with session.lock:
                        if session.job_status == JobStatus.RUNNING:
                            session.job_status = JobStatus.KILLED
                            session.final_message = self._stop_reason

                # Gang timeout applies only before the first barrier pass —
                # a preemption relaunch transiently un-registers one task and
                # must not trip it.
                if not handler._all_registered_fired and \
                        time.monotonic() - start > gang_timeout_s:
                    with session.lock:
                        for t in session.tasks():
                            if t.spec is None and not t.status.is_terminal:
                                session.on_task_lost(
                                    t, f"not registered within gang timeout "
                                       f"({gang_timeout_s:.0f}s)")
                        if session.job_status == JobStatus.RUNNING:
                            session.job_status = JobStatus.FAILED
                            session.final_message = "gang allocation timed out"
                if app_timeout_s and time.monotonic() - start > app_timeout_s:
                    with session.lock:
                        if session.job_status == JobStatus.RUNNING:
                            session.job_status = JobStatus.FAILED
                            session.final_message = (
                                f"application exceeded "
                                f"tony.application.timeout-ms")
                if session.is_done():
                    break
                if self._resize_relaunch:
                    # Drained gang committed; the attempt ends here and
                    # run() relaunches at the new size (normal teardown
                    # below reaps the already-exited containers).
                    break
                time.sleep(_TICK_S)
        finally:
            launch_pool.shutdown(wait=True)
            # Teardown: untracked sidecars and any stragglers die with the job.
            session.kill_remaining(
                f"job finished: {session.job_status.value}")
            self._stop_task_containers(session)
            self.scheduler.poll_completed()
            am_adapter.stop()
            if self.events is not None:
                for t in session.tasks():
                    self.events.task_finished(
                        t.job_type, t.index, t.status.value, t.exit_code,
                        t.diagnostics, t.metrics)
        # Checkpoint plane: what the executors reported committed this
        # attempt (heartbeat piggyback) — the step the NEXT attempt's
        # restore_on_start will resume from after a gang restart.
        ckpt_step = session.last_committed_step()
        self._log(f"attempt {attempt_id}: {session.job_status.value} "
                  f"- {session.final_message}"
                  + (f" (last committed ckpt step: {ckpt_step})"
                     if ckpt_step is not None else ""))
        return session.job_status

    # -- whole application -------------------------------------------------
    def run(self) -> int:
        conf = self.conf
        conf.validate()
        conf.save(self.job_dir / constants.TONY_JOB_JSON)
        history = conf.get(conf_mod.HISTORY_LOCATION) or str(
            self.job_dir / "history")
        self.history_dir = Path(history)
        self.events = EventHandler(
            history, self.app_id,
            conf_snapshot=dict(conf.items()),
            app_name=conf.get(conf_mod.APPLICATION_NAME, ""))
        retries = conf.get_int(conf_mod.AM_RETRY_COUNT, 0)
        status = JobStatus.FAILED
        try:
            attempt = 1
            retries_used = 0
            while True:
                status = self.run_attempt(attempt)
                if self._resize_relaunch and self._resize is not None \
                        and self._resize.active:
                    # Elastic re-gang: the drained gang's commit is
                    # durable, so apply the new topology and relaunch —
                    # WITHOUT consuming the gang-restart retry budget
                    # (resizes have their own: tony.resize.max-resizes).
                    self._resize_relaunch = False
                    spec = self._resize.spec
                    conf.set(conf_mod.instances_key(spec.job_type),
                             str(spec.new_workers))
                    conf.save(self.job_dir / constants.TONY_JOB_JSON)
                    ckpt_step = (self.session.last_committed_step()
                                 if self.session else None)
                    self._log(
                        f"resize re-gang: relaunching "
                        f"{spec.new_workers} {spec.job_type}(s)"
                        + (f"; resuming from committed ckpt step "
                           f"{ckpt_step}" if ckpt_step is not None
                           else ""))
                    attempt += 1
                    continue
                if status in (JobStatus.SUCCEEDED, JobStatus.KILLED):
                    break
                if retries_used < retries:
                    retries_used += 1
                    ckpt_step = (self.session.last_committed_step()
                                 if self.session else None)
                    self._log(
                        f"attempt {attempt} failed; gang restart "
                        f"({retries_used}/{retries} retries used)"
                        + (f"; resuming from committed ckpt step "
                           f"{ckpt_step}" if ckpt_step is not None
                           else ""))
                    attempt += 1
                    continue
                break
        finally:
            if self._resize is not None and self._resize.active:
                # A terminal AM must never leave a phase dangling — the
                # degrade verdict (and its RESIZE record) lands before
                # the event log closes.
                self._resize.abandon("application finished")
                self._resize = None
            self.final_status = status
            self.final_message = (self.session.final_message
                                  if self.session else "")
            self.events.application_finished(status.value, self.final_message)
            self.events.close()
            (self.job_dir / FINAL_STATUS_FILE).write_text(
                json.dumps({
                    "status": status.value,
                    "message": self.final_message,
                    "app_id": self.app_id,
                    # Terminal task snapshot so the client can report final
                    # transitions even after the RPC server is gone.
                    "task_infos": (self.session.task_infos()
                                   if self.session else []),
                }))
            self.scheduler.stop()
            if self.server is not None:
                # Give the client one last poll window before the socket dies.
                time.sleep(0.1)
                self.server.stop()
        return (constants.EXIT_SUCCESS if status == JobStatus.SUCCEEDED
                else constants.EXIT_FAILURE)
