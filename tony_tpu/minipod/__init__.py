"""MiniPod: the in-process dev/test cluster (MiniYARNCluster analogue).

The reference's single best testing idea (SURVEY.md §4): a full
RM+NM+HDFS inside one JUnit JVM, launching containers as REAL local
processes, so every failure semantic — heartbeat expiry, gang barriers,
fail-fast, preemption — is exercised against live executors rather than
mocks. MiniPod is that trick for this framework: the AM runs on a thread in
the calling process, containers are real ``python -m tony_tpu.executor``
subprocesses via :class:`~tony_tpu.scheduler.LocalProcessScheduler`, and the
caller gets the live :class:`~tony_tpu.am.ApplicationMaster` to poke at
(preempt containers, inspect the session) while the job runs.

Also the substance of ``tony-mini`` (the reference's docker pseudo-cluster,
SURVEY.md §2.2) — here no docker is needed because the substrate is plain
processes.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path
from typing import Dict, Optional

from tony_tpu.am import ApplicationMaster
from tony_tpu.conf import TonyConfig
from tony_tpu.scheduler import LocalProcessScheduler
from tony_tpu.session import JobStatus


class MiniPodJob:
    """A running (or finished) MiniPod job: join it, or reach into the live
    AM/session/scheduler mid-flight."""

    def __init__(self, am: ApplicationMaster):
        self.am = am
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"minipod-{am.app_id}")
        self.exit_code: Optional[int] = None

    def _run(self) -> None:
        self.exit_code = self.am.run()

    def start(self) -> "MiniPodJob":
        self._thread.start()
        return self

    @property
    def session(self):
        return self.am.session

    @property
    def scheduler(self) -> LocalProcessScheduler:
        return self.am.scheduler  # type: ignore[return-value]

    def wait(self, timeout: float = 60.0) -> int:
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError(
                f"MiniPod job {self.am.app_id} still running after {timeout}s")
        assert self.exit_code is not None
        return self.exit_code

    def wait_for(self, predicate, timeout: float = 30.0, what: str = ""):
        """Poll a predicate over the live job (e.g. "task running") —
        the e2e tests' synchronization primitive."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            value = predicate()
            if value:
                return value
            time.sleep(0.02)
        raise TimeoutError(f"MiniPod wait_for timed out: {what}")

    def kill(self, reason: str = "killed by test") -> None:
        if self.session is not None:
            from tony_tpu.rpc import ApplicationRpcHandler
            handler = self.am.handler
            if handler is not None:
                handler.rpc_finish_application(reason=reason)


class MiniPod:
    """Factory for MiniPod jobs rooted in one work directory."""

    _counter = 0

    def __init__(self, workdir: str | Path):
        self.workdir = Path(workdir)
        self.workdir.mkdir(parents=True, exist_ok=True)

    def submit(self, props: Dict[str, str],
               src_dir: Optional[str | Path] = None,
               app_id: Optional[str] = None) -> MiniPodJob:
        """Build a job from config props (fast heartbeats defaulted for test
        speed), optionally stage ``src_dir``, start the AM thread."""
        MiniPod._counter += 1
        app_id = app_id or f"app_minipod_{MiniPod._counter:04d}"
        conf = TonyConfig({
            "tony.task.heartbeat-interval-ms": "200",
            "tony.am.gang-allocation-timeout-ms": "30000",
            **{str(k): str(v) for k, v in props.items()},
        })
        job_dir = self.workdir / app_id
        job_dir.mkdir(parents=True, exist_ok=True)
        if src_dir is not None:
            import shutil
            dest = job_dir / "src"
            if not dest.exists():
                shutil.copytree(src_dir, dest)
        am = ApplicationMaster(conf, app_id=app_id, job_dir=job_dir)
        return MiniPodJob(am).start()

    def run(self, props: Dict[str, str],
            src_dir: Optional[str | Path] = None,
            timeout: float = 60.0) -> MiniPodJob:
        """Submit and wait; returns the finished job."""
        job = self.submit(props, src_dir=src_dir)
        job.wait(timeout)
        return job
