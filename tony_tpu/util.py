"""Small shared helpers (reference: the ``com.linkedin.tony.util.Utils``
grab-bag, kept deliberately tiny here — SURVEY.md §2.1)."""

from __future__ import annotations

import os
from pathlib import Path
from typing import Dict

# The repo/package root: parent of the tony_tpu package directory.
PKG_ROOT = str(Path(__file__).resolve().parent.parent)


def default_workdir() -> Path:
    """The client job workdir — TONY_WORK_DIR env or ~/.tony-tpu/jobs.
    Shared by the client (write side) and history CLI (scan side) so
    `tony history` finds what `tony submit` wrote."""
    return Path(os.environ.get("TONY_WORK_DIR",
                               Path.home() / ".tony-tpu" / "jobs"))


def child_pythonpath(env: Dict[str, str]) -> str:
    """PYTHONPATH for a child process that must import ``tony_tpu`` even when
    the parent loaded it off ``sys.path`` (tests / source checkout) rather
    than an installed package: prepend the package root, dedupe."""
    parts = [PKG_ROOT] + [p for p in env.get("PYTHONPATH", "").split(
        os.pathsep) if p and p != PKG_ROOT]
    return os.pathsep.join(parts)
