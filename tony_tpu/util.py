"""Small shared helpers (reference: the ``com.linkedin.tony.util.Utils``
grab-bag, kept deliberately tiny here — SURVEY.md §2.1)."""

from __future__ import annotations

import os
from pathlib import Path
from typing import Dict

# The repo/package root: parent of the tony_tpu package directory.
PKG_ROOT = str(Path(__file__).resolve().parent.parent)


def default_workdir() -> Path:
    """The client job workdir — TONY_WORK_DIR env or ~/.tony-tpu/jobs.
    Shared by the client (write side) and history CLI (scan side) so
    `tony history` finds what `tony submit` wrote."""
    return Path(os.environ.get("TONY_WORK_DIR",
                               Path.home() / ".tony-tpu" / "jobs"))


def child_pythonpath(env: Dict[str, str]) -> str:
    """PYTHONPATH for a child process that must import ``tony_tpu`` even when
    the parent loaded it off ``sys.path`` (tests / source checkout) rather
    than an installed package: prepend the package root, dedupe.

    Deliberately does NOT carry site-packages: PYTHONPATH reaches the USER
    process, where host site dirs would shadow a job venv's packages. The
    ``python -S`` control-plane processes get their site dirs via
    :func:`control_plane_site_env` / :func:`restore_site_dirs` instead."""
    parts = [PKG_ROOT] + [p for p in env.get("PYTHONPATH", "").split(
        os.pathsep) if p and p != PKG_ROOT]
    return os.pathsep.join(parts)


# AM/executor processes launch with `python -S`: the ML stack's
# sitecustomize hooks cost ~1.8 s per interpreter start (measured: the
# whole control-plane import tree is 0.15 s without them) — pure
# submit→running latency for stdlib-only processes. Their LAZY heavyweight
# imports (discovery's jax census, the trace collector's profiler client)
# still need site-packages, carried in this env var and restored with
# site.addsitedir (which, unlike PYTHONPATH, also processes .pth files —
# pip --user and editable installs keep working).
ENV_SITE_DIRS = "TONY_SITE_DIRS"


def control_plane_site_env() -> Dict[str, str]:
    """Env entry shipping this interpreter's site dirs to a ``-S`` child.
    Reuses an inherited value (an AM is itself a ``-S`` child and must
    forward what the client computed under full site)."""
    import site

    existing = os.environ.get(ENV_SITE_DIRS)
    if existing:
        return {ENV_SITE_DIRS: existing}
    dirs = []
    try:
        dirs += site.getsitepackages()
    except AttributeError:        # some embedded interpreters
        pass
    try:
        user = site.getusersitepackages()
        if user:
            dirs.append(user)
    except AttributeError:
        pass
    dirs = [d for d in dirs if os.path.isdir(d)]
    return {ENV_SITE_DIRS: os.pathsep.join(dirs)} if dirs else {}


def restore_site_dirs() -> None:
    """First statement of a ``-S`` control-plane ``__main__``: register the
    shipped site dirs so lazy imports resolve, WITHOUT running the
    sitecustomize hooks ``-S`` exists to skip."""
    import site

    for d in os.environ.get(ENV_SITE_DIRS, "").split(os.pathsep):
        if d:
            site.addsitedir(d)


def normalize_serve_telemetry(raw: Dict) -> Dict[str, object]:
    """One normalization for the serve heartbeat schema, shared by the
    executor's stats-file reader and the session's heartbeat ingest so
    the two layers cannot drift: scalars become floats, list values
    (the router's ``prefix_digest`` block-key list and the parked-
    conversation ``parked_digest`` list) become string lists, and
    non-numeric strings (the disaggregated replica ``role``
    — the schema's second non-scalar) pass through as strings, and the
    per-tenant ``tenants`` breakdown (tony_tpu.serve.qos — a dict of
    per-tenant dicts of scalars, the schema's ONE sanctioned nesting)
    normalizes recursively. Numeric strings still normalize to float,
    so a stats writer that stringified a counter keeps its historical
    behavior. Raises on anything else (deeper nesting, None), so both
    callers keep their own advisory-telemetry failure handling."""
    def norm(v: object, depth: int) -> object:
        if isinstance(v, (list, tuple)):
            return [str(x) for x in v]
        if isinstance(v, dict):
            if depth >= 3:
                raise TypeError(
                    "serve telemetry nests deeper than the schema's "
                    "tenants breakdown (dict of dicts of scalars)")
            return {str(k): norm(x, depth + 1) for k, x in v.items()}
        if isinstance(v, str):
            try:
                return float(v)
            except ValueError:
                return v
        return float(v)

    return {str(k): norm(v, 1) for k, v in dict(raw).items()}
