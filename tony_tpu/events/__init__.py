"""Lifecycle event log: the jhist write/read path.

Mirrors ``com.linkedin.tony.events`` (``EventHandler`` + the Avro ``Event``
schema under ``tony-core/src/main/avro/``, unverified — SURVEY.md §0/§3.5).
The reference buffers Avro records and writes ``<appId>.jhist`` to an HDFS
intermediate dir, moving it to the finished dir on completion; here the
serialization is JSON-lines (SURVEY.md §7 design stance: "JSON-lines events
instead of Avro jhist — same producer/consumer split") and the store is a
plain directory tree::

    <history>/intermediate/<appId>.jhist.inprogress   (while running)
    <history>/finished/<appId>.jhist                  (after completion)

Event types carried over: APPLICATION_INITED, TASK_STARTED, TASK_FINISHED,
APPLICATION_FINISHED. The first line of every jhist file is a metadata record
(user, app name, started timestamp, config snapshot) so the history server
can render a job without re-reading its config files.
"""

from __future__ import annotations

import getpass
import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional

from tony_tpu import constants

APPLICATION_INITED = "APPLICATION_INITED"
TASK_STARTED = "TASK_STARTED"
TASK_METRICS = "TASK_METRICS"
ALL_TASKS_RUNNING = "ALL_TASKS_RUNNING"
TASK_FINISHED = "TASK_FINISHED"
APPLICATION_FINISHED = "APPLICATION_FINISHED"

_METADATA = "METADATA"


class EventHandler:
    """Append-only jhist writer owned by the AM (reference: ``EventHandler``
    producer thread; here writes are cheap enough to do inline under a lock)."""

    def __init__(self, history_dir: str | Path, app_id: str,
                 conf_snapshot: Optional[Dict[str, str]] = None,
                 app_name: str = ""):
        self.history_dir = Path(history_dir)
        self.app_id = app_id
        self._lock = threading.Lock()
        inter = self.history_dir / constants.EVENTS_DIR_INTERMEDIATE
        inter.mkdir(parents=True, exist_ok=True)
        self.inprogress_path = inter / (
            app_id + constants.JHIST_INPROGRESS_SUFFIX)
        self.finished_path = (self.history_dir / constants.EVENTS_DIR_FINISHED
                              / (app_id + constants.JHIST_SUFFIX))
        self._file = open(self.inprogress_path, "a", encoding="utf-8")
        self._closed = False
        self._write({
            "type": _METADATA,
            "timestamp": time.time(),
            "payload": {
                "app_id": app_id,
                "app_name": app_name,
                "user": getpass.getuser(),
                "started": time.time(),
                "config": dict(conf_snapshot or {}),
            },
        })

    def _write(self, record: Dict[str, Any]) -> None:
        with self._lock:
            if self._closed:
                return
            self._file.write(json.dumps(record, sort_keys=True) + "\n")
            self._file.flush()

    def emit(self, event_type: str, **payload: Any) -> None:
        self._write({"type": event_type, "timestamp": time.time(),
                     "payload": payload})

    # -- convenience emitters matching the reference's event vocabulary ----
    def application_inited(self, attempt_id: int, num_tasks: int) -> None:
        self.emit(APPLICATION_INITED, attempt_id=attempt_id,
                  num_tasks=num_tasks)

    def task_started(self, job_type: str, index: int, host: str) -> None:
        self.emit(TASK_STARTED, job_type=job_type, index=index, host=host)

    def task_metrics(self, job_type: str, index: int,
                     metrics: Dict[str, float]) -> None:
        """One TaskMonitor sample — the per-task metrics *timeline* the
        portal renders (reference: MetricsRpc history, not just the final
        snapshot in TASK_FINISHED)."""
        self.emit(TASK_METRICS, job_type=job_type, index=index,
                  metrics=dict(metrics))

    def all_running(self, attempt_id: int,
                    submit_to_running_s: Optional[float] = None) -> None:
        """Gang barrier passed: every task is RUNNING. Carries the
        submit→all-RUNNING latency when the client shipped its submit
        timestamp (BASELINE.md secondary metric)."""
        self.emit(ALL_TASKS_RUNNING, attempt_id=attempt_id,
                  submit_to_running_s=submit_to_running_s)

    def task_finished(self, job_type: str, index: int, status: str,
                      exit_code: Optional[int], diagnostics: str = "",
                      metrics: Optional[Dict[str, float]] = None) -> None:
        self.emit(TASK_FINISHED, job_type=job_type, index=index,
                  status=status, exit_code=exit_code,
                  diagnostics=diagnostics, metrics=metrics or {})

    def application_finished(self, status: str, message: str = "") -> None:
        self.emit(APPLICATION_FINISHED, status=status, message=message)

    def close(self) -> None:
        """Finalize: move intermediate → finished (the reference's HDFS
        rename on job completion)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._file.close()
        self.finished_path.parent.mkdir(parents=True, exist_ok=True)
        os.replace(self.inprogress_path, self.finished_path)


# ---------------------------------------------------------------------------
# Read path (consumed by the history server and by tests)
# ---------------------------------------------------------------------------

# Parse cache keyed by (mtime_ns, size): finished jhists are immutable and
# in-progress ones only append, so an unchanged stat means an unchanged
# parse. The reference keeps an in-memory cache with a refresh thread in the
# history server (SURVEY.md §3.5); stat-on-read gives the same zero-reparse
# behavior without a thread, and TASK_METRICS growth (one record per task
# per 5s) makes re-parsing per page hit O(job runtime) without it.
_CACHE_MAX_FILES = 512
_parse_cache: Dict[str, tuple] = {}   # path -> (mtime_ns, size, records)
_meta_cache: Dict[str, tuple] = {}    # path -> (mtime_ns, metadata)
_parse_cache_lock = threading.Lock()


def _parse_file(path: str | Path) -> List[Dict[str, Any]]:
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def read_events(path: str | Path) -> List[Dict[str, Any]]:
    """Parse one jhist (or .inprogress) file into its event records.
    Cached on (mtime, size); callers must not mutate the returned records."""
    key = str(path)
    try:
        st = os.stat(path)
    except OSError:
        # e.g. intermediate→finished rename raced the scan; no stale cache.
        with _parse_cache_lock:
            _parse_cache.pop(key, None)
        raise
    with _parse_cache_lock:
        hit = _parse_cache.get(key)
        if hit is not None and hit[0] == st.st_mtime_ns and hit[1] == st.st_size:
            # Shallow copy: the list is the mutation surface callers
            # actually touch (sort/filter/append); handing out the cached
            # list itself would let one caller poison every later read.
            return list(hit[2])
    records = _parse_file(path)
    with _parse_cache_lock:
        if len(_parse_cache) >= _CACHE_MAX_FILES:
            # Drop the oldest insertion — plain dicts iterate in insertion
            # order; good enough for a bound, no LRU bookkeeping needed.
            _parse_cache.pop(next(iter(_parse_cache)))
        _parse_cache[key] = (st.st_mtime_ns, st.st_size, records)
    return list(records)


def job_metadata(path: str | Path) -> Dict[str, Any]:
    """The metadata record (first line) of a jhist file. Served from the
    parse cache when the file is already cached; reads only the first line
    otherwise (the list page must not force full parses of every job)."""
    key = str(path)
    try:
        st = os.stat(path)
    except OSError:
        st = None
    if st is not None:
        with _parse_cache_lock:
            hit = _parse_cache.get(key)
            if hit is not None and hit[0] == st.st_mtime_ns \
                    and hit[1] == st.st_size:
                recs = hit[2]
                if recs and recs[0].get("type") == _METADATA:
                    return recs[0].get("payload", {})
                return {}
    if st is not None:
        with _parse_cache_lock:
            hit = _meta_cache.get(key)
            if hit is not None and hit[0] == st.st_mtime_ns:
                return hit[1]
    with open(path, encoding="utf-8") as f:
        first = f.readline().strip()
    rec = json.loads(first) if first else {}
    meta = rec.get("payload", {}) if rec.get("type") == _METADATA else {}
    if st is not None:
        with _parse_cache_lock:
            if len(_meta_cache) >= _CACHE_MAX_FILES:
                _meta_cache.pop(next(iter(_meta_cache)))
            # mtime alone suffices: the metadata line is written once at
            # file creation and never rewritten.
            _meta_cache[key] = (st.st_mtime_ns, meta)
    return meta


def list_jobs(history_dir: str | Path) -> Iterator[Dict[str, Any]]:
    """All jobs under a history root, finished first then in-progress —
    the history server's scan (reference: HDFS scan in ParserUtils)."""
    root = Path(history_dir)
    for sub, suffix, state in (
            (constants.EVENTS_DIR_FINISHED, constants.JHIST_SUFFIX, "finished"),
            (constants.EVENTS_DIR_INTERMEDIATE,
             constants.JHIST_INPROGRESS_SUFFIX, "running")):
        d = root / sub
        if not d.is_dir():
            continue
        for p in sorted(d.iterdir()):
            if not p.name.endswith(suffix):
                continue
            app_id = p.name[:-len(suffix)]
            meta = job_metadata(p)
            yield {"app_id": app_id, "state": state, "path": str(p),
                   "metadata": meta}
