"""Lifecycle event log: the jhist write/read path.

Mirrors ``com.linkedin.tony.events`` (``EventHandler`` + the Avro ``Event``
schema under ``tony-core/src/main/avro/``, unverified — SURVEY.md §0/§3.5).
The reference buffers Avro records and writes ``<appId>.jhist`` to an HDFS
intermediate dir, moving it to the finished dir on completion; here the
serialization is JSON-lines (SURVEY.md §7 design stance: "JSON-lines events
instead of Avro jhist — same producer/consumer split") and the store is a
plain directory tree::

    <history>/intermediate/<appId>.jhist.inprogress   (while running)
    <history>/finished/<appId>.jhist                  (after completion)

Event types carried over: APPLICATION_INITED, TASK_STARTED, TASK_FINISHED,
APPLICATION_FINISHED. The first line of every jhist file is a metadata record
(user, app name, started timestamp, config snapshot) so the history server
can render a job without re-reading its config files.

PR 18 makes the log LOAD-BEARING, not decorative — three widenings:

* SERVE_WINDOW — one per-heartbeat serve stats window per task, sourced
  from the SAME normalized heartbeat schema the session/router consume
  (no second bookkeeping path): latency p50/p99, qps, queue depth,
  admission rejections, prefix-hit/handoff/park/AOT counters, and the
  per-tenant breakdown. The history portal's SLO dashboards and the
  per-tenant rollups render from exactly these records.
* TRAIN_STEP — per-step wall time, collective bytes (from
  ``profiler.collective_report()``) and an MFU estimate, fed through
  the executor's stats-file pickup like serve stats.
* SCALE_DECISION — a SELF-VERIFYING autoscale record: the full decide()
  input (policy fields, active count, samples, clock, last action) plus
  the delta the live AM took, so replaying the log through
  ``scaling.replay_decisions`` reproduces the run's scale decisions
  exactly (floats round-trip bit-exact through JSON).

High-rate records are bounded: with ``max_bytes`` armed the writer
compacts through the ckpt plane's stage-and-rename idiom — lifecycle
events survive whole, the newest half of the high-rate tail is kept.
The write path stays jax-free.
"""

from __future__ import annotations

import getpass
import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional

from tony_tpu import chaos, constants

APPLICATION_INITED = "APPLICATION_INITED"
TASK_STARTED = "TASK_STARTED"
TASK_METRICS = "TASK_METRICS"
ALL_TASKS_RUNNING = "ALL_TASKS_RUNNING"
TASK_FINISHED = "TASK_FINISHED"
APPLICATION_FINISHED = "APPLICATION_FINISHED"
SERVE_WINDOW = "SERVE_WINDOW"
TRAIN_STEP = "TRAIN_STEP"
SCALE_DECISION = "SCALE_DECISION"
RESIZE = "RESIZE"
# Continuous weight publication (tony_tpu.publish / serve.swap): one
# PUBLISH per new manifest pointer the train gang stages, one SWAP per
# replica the AM rolls onto it — together the timeline `tony history`
# reconstructs (which version, which step, who swapped when, how long
# each swap window lasted). Low-rate lifecycle records: NEVER rotation
# victims.
PUBLISH = "PUBLISH"
SWAP = "SWAP"

_METADATA = "METADATA"

# Record types a long run emits continuously (one per task heartbeat /
# train step): rotation's compaction victims. Lifecycle events,
# SCALE_DECISION (low-rate, replay-bearing) and RESIZE (a handful per
# job, the recovery timeline) always survive whole.
_HIGH_RATE = frozenset({TASK_METRICS, SERVE_WINDOW, TRAIN_STEP})


class EventHandler:
    """Append-only jhist writer owned by the AM (reference: ``EventHandler``
    producer thread; here writes are cheap enough to do inline under a lock)."""

    def __init__(self, history_dir: str | Path, app_id: str,
                 conf_snapshot: Optional[Dict[str, str]] = None,
                 app_name: str = "", max_bytes: int = 0):
        self.history_dir = Path(history_dir)
        self.app_id = app_id
        # Bounded rotation (0 = unbounded): past max_bytes the writer
        # COMPACTS in place through stage-and-rename (lifecycle events
        # whole, newest half of the high-rate tail) — a week-long serve
        # job's log stays a bounded file, never an unbounded append.
        self.max_bytes = int(max_bytes)
        self.rotations = 0
        self._lock = threading.Lock()
        inter = self.history_dir / constants.EVENTS_DIR_INTERMEDIATE
        inter.mkdir(parents=True, exist_ok=True)
        self.inprogress_path = inter / (
            app_id + constants.JHIST_INPROGRESS_SUFFIX)
        self.finished_path = (self.history_dir / constants.EVENTS_DIR_FINISHED
                              / (app_id + constants.JHIST_SUFFIX))
        self._file = open(self.inprogress_path, "a", encoding="utf-8")
        self._closed = False
        self._write({
            "type": _METADATA,
            "timestamp": time.time(),
            "payload": {
                "app_id": app_id,
                "app_name": app_name,
                "user": getpass.getuser(),
                "started": time.time(),
                "config": dict(conf_snapshot or {}),
            },
        })

    def _write(self, record: Dict[str, Any]) -> None:
        with self._lock:
            if self._closed:
                return
            self._file.write(json.dumps(record, sort_keys=True) + "\n")
            self._file.flush()
            if self.max_bytes and self._file.tell() > self.max_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Compact the inprogress file past ``max_bytes`` (caller holds
        the lock): keep the metadata line, every lifecycle/scale record,
        and the newest half of the high-rate tail, staged to a sibling
        tmp and ``os.replace``d over the live path — the ckpt plane's
        atomic stage-and-rename idiom, so a concurrent reader sees the
        old file or the compacted one, never a torn half."""
        self._file.close()
        try:
            records = _parse_file(self.inprogress_path)
        except (OSError, ValueError):
            # Unreadable under external interference: keep appending —
            # rotation is a bound, never a reason to lose the log.
            self._file = open(self.inprogress_path, "a", encoding="utf-8")
            return
        keep = [r for r in records if r.get("type") not in _HIGH_RATE]
        high = [r for r in records if r.get("type") in _HIGH_RATE]
        keep += high[len(high) // 2:]
        keep.sort(key=lambda r: r.get("timestamp", 0.0))
        # Chaos crash sites (tony_tpu.chaos): a kill -9 anywhere in the
        # stage-and-rename must leave the OLD log (before the replace)
        # or the NEW compacted one (after) — never a torn file. The
        # fault-injection sweep pins all three boundaries.
        chaos.crash_point("rotate_before_stage")
        tmp = Path(f"{self.inprogress_path}.tmp.{os.getpid()}")
        with open(tmp, "w", encoding="utf-8") as fh:
            for r in keep:
                fh.write(json.dumps(r, sort_keys=True) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        chaos.crash_point("rotate_after_stage")
        os.replace(tmp, self.inprogress_path)
        chaos.crash_point("rotate_after_replace")
        self._file = open(self.inprogress_path, "a", encoding="utf-8")
        self.rotations += 1

    def emit(self, event_type: str, **payload: Any) -> None:
        self._write({"type": event_type, "timestamp": time.time(),
                     "payload": payload})

    # -- convenience emitters matching the reference's event vocabulary ----
    def application_inited(self, attempt_id: int, num_tasks: int) -> None:
        self.emit(APPLICATION_INITED, attempt_id=attempt_id,
                  num_tasks=num_tasks)

    def task_started(self, job_type: str, index: int, host: str) -> None:
        self.emit(TASK_STARTED, job_type=job_type, index=index, host=host)

    def task_metrics(self, job_type: str, index: int,
                     metrics: Dict[str, float]) -> None:
        """One TaskMonitor sample — the per-task metrics *timeline* the
        portal renders (reference: MetricsRpc history, not just the final
        snapshot in TASK_FINISHED)."""
        self.emit(TASK_METRICS, job_type=job_type, index=index,
                  metrics=dict(metrics))

    def all_running(self, attempt_id: int,
                    submit_to_running_s: Optional[float] = None) -> None:
        """Gang barrier passed: every task is RUNNING. Carries the
        submit→all-RUNNING latency when the client shipped its submit
        timestamp (BASELINE.md secondary metric)."""
        self.emit(ALL_TASKS_RUNNING, attempt_id=attempt_id,
                  submit_to_running_s=submit_to_running_s)

    def task_finished(self, job_type: str, index: int, status: str,
                      exit_code: Optional[int], diagnostics: str = "",
                      metrics: Optional[Dict[str, float]] = None) -> None:
        self.emit(TASK_FINISHED, job_type=job_type, index=index,
                  status=status, exit_code=exit_code,
                  diagnostics=diagnostics, metrics=metrics or {})

    def application_finished(self, status: str, message: str = "") -> None:
        self.emit(APPLICATION_FINISHED, status=status, message=message)

    # -- PR 18 vocabulary: the load-bearing serve/train/scale records ------
    def serve_window(self, job_type: str, index: int,
                     stats: Dict[str, Any]) -> None:
        """One per-heartbeat serve stats window for one task — the
        ALREADY-normalized heartbeat dict (session.Task.serve_metrics),
        verbatim: the log is a recording of the schema the fleet
        already speaks, never a second bookkeeping path."""
        self.emit(SERVE_WINDOW, job_type=job_type, index=index,
                  stats=dict(stats))

    def train_step(self, job_type: str, index: int, step: int,
                   step_time_s: float, collective_bytes: float = 0.0,
                   mfu: float = 0.0) -> None:
        """One training step's cost triple: wall time, collective bytes
        (``profiler.collective_report()``'s total for the step plane),
        and the caller's MFU estimate — the portal's per-step trend
        across BENCH rounds."""
        self.emit(TRAIN_STEP, job_type=job_type, index=index,
                  step=int(step), step_time_s=float(step_time_s),
                  collective_bytes=float(collective_bytes),
                  mfu=float(mfu))

    def scale_decision(self, job_type: str, delta: int, n_active: int,
                       samples: List[Dict[str, Any]], now: float,
                       last_action: Optional[float],
                       policy: Dict[str, Any]) -> None:
        """A SELF-VERIFYING autoscale record: everything
        ``scaling.decide`` consumed (policy fields, active count,
        samples, clock, last action) plus the delta the live AM took —
        ``scaling.replay_decisions`` recomputes the decision from these
        fields and must reproduce it exactly."""
        self.emit(SCALE_DECISION, job_type=job_type, delta=int(delta),
                  n_active=int(n_active),
                  samples=[dict(s) for s in samples], now=float(now),
                  last_action=last_action, policy=dict(policy))

    def resize(self, phase: str, trigger: str, job_type: str,
               old_workers: int, new_workers: int, wall_s: float,
               ok: bool, detail: str = "") -> None:
        """One resize-phase record (tony_tpu.am.resize): the phase name
        (DRAINING / RE-GANG / RESTORING, or DEGRADED when the machine
        fell back to the full gang restart), what triggered the resize,
        the old→new topology, and the phase's wall seconds — `tony
        history` renders these as the recovery timeline."""
        self.emit(RESIZE, phase=str(phase), trigger=str(trigger),
                  job_type=job_type, old_workers=int(old_workers),
                  new_workers=int(new_workers), wall_s=float(wall_s),
                  ok=bool(ok), detail=detail)

    def publish(self, version: int, step: int, note: str = "") -> None:
        """One new weight publication became the fleet's swap target
        (tony_tpu.publish): the version the pointer file minted and the
        committed checkpoint step it names. Emitted by the AM when its
        publication tick first observes the version — exactly once per
        version, however many heartbeats carry it."""
        self.emit(PUBLISH, version=int(version), step=int(step),
                  note=str(note))

    def swap(self, job_type: str, index: int, from_version: int,
             to_version: int, step: int, wall_s: float, ok: bool,
             detail: str = "") -> None:
        """One replica's hot-swap outcome (tony_tpu.serve.swap): which
        versions it rolled between, the step restored, and the wall
        seconds of the whole window (restore + quiesce + flip) — the
        number ROOFLINE §16's swap-window model predicts. ok=False
        records a rolled-back attempt: the replica kept serving
        from_version."""
        self.emit(SWAP, job_type=job_type, index=int(index),
                  from_version=int(from_version),
                  to_version=int(to_version), step=int(step),
                  wall_s=float(wall_s), ok=bool(ok), detail=detail)

    def close(self) -> None:
        """Finalize: move intermediate → finished (the reference's HDFS
        rename on job completion)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._file.close()
        self.finished_path.parent.mkdir(parents=True, exist_ok=True)
        os.replace(self.inprogress_path, self.finished_path)


# ---------------------------------------------------------------------------
# Read path (consumed by the history server and by tests)
# ---------------------------------------------------------------------------

# Parse cache keyed by (mtime_ns, size): finished jhists are immutable and
# in-progress ones only append, so an unchanged stat means an unchanged
# parse. The reference keeps an in-memory cache with a refresh thread in the
# history server (SURVEY.md §3.5); stat-on-read gives the same zero-reparse
# behavior without a thread, and TASK_METRICS growth (one record per task
# per 5s) makes re-parsing per page hit O(job runtime) without it.
_CACHE_MAX_FILES = 512
_parse_cache: Dict[str, tuple] = {}   # path -> (mtime_ns, size, records)
_meta_cache: Dict[str, tuple] = {}    # path -> (mtime_ns, metadata)
_parse_cache_lock = threading.Lock()


def _parse_file(path: str | Path) -> List[Dict[str, Any]]:
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _finished_sibling(path: str | Path) -> Optional[Path]:
    """The finished-dir path an intermediate jhist lands at when
    ``EventHandler.close()`` renames it — the retry target for the
    scan-vs-close race. None for paths that are not intermediates."""
    p = Path(path)
    if not p.name.endswith(constants.JHIST_INPROGRESS_SUFFIX):
        return None
    app_id = p.name[:-len(constants.JHIST_INPROGRESS_SUFFIX)]
    return (p.parent.parent / constants.EVENTS_DIR_FINISHED
            / (app_id + constants.JHIST_SUFFIX))


def read_events(path: str | Path) -> List[Dict[str, Any]]:
    """Parse one jhist (or .inprogress) file into its event records.
    Cached on (mtime, size); callers must not mutate the returned
    records. An intermediate path that vanished underneath us — the
    ``list_jobs`` scan racing ``EventHandler.close()``'s rename —
    retries at the finished path instead of raising: the records exist,
    they just moved."""
    key = str(path)
    try:
        st = os.stat(path)
    except OSError:
        # e.g. intermediate→finished rename raced the scan; no stale cache.
        with _parse_cache_lock:
            _parse_cache.pop(key, None)
        fin = _finished_sibling(path)
        if fin is not None and fin.exists():
            return read_events(fin)
        raise
    with _parse_cache_lock:
        hit = _parse_cache.get(key)
        if hit is not None and hit[0] == st.st_mtime_ns and hit[1] == st.st_size:
            # Shallow copy: the list is the mutation surface callers
            # actually touch (sort/filter/append); handing out the cached
            # list itself would let one caller poison every later read.
            return list(hit[2])
    try:
        records = _parse_file(path)
    except OSError:
        # stat won the race, open lost it: same rename, same retry.
        with _parse_cache_lock:
            _parse_cache.pop(key, None)
        fin = _finished_sibling(path)
        if fin is not None and fin.exists():
            return read_events(fin)
        raise
    with _parse_cache_lock:
        if len(_parse_cache) >= _CACHE_MAX_FILES:
            # Drop the oldest insertion — plain dicts iterate in insertion
            # order; good enough for a bound, no LRU bookkeeping needed.
            _parse_cache.pop(next(iter(_parse_cache)))
        _parse_cache[key] = (st.st_mtime_ns, st.st_size, records)
    return list(records)


def job_metadata(path: str | Path) -> Dict[str, Any]:
    """The metadata record (first line) of a jhist file. Served from the
    parse cache when the file is already cached; reads only the first line
    otherwise (the list page must not force full parses of every job)."""
    key = str(path)
    try:
        st = os.stat(path)
    except OSError:
        st = None
    if st is not None:
        with _parse_cache_lock:
            hit = _parse_cache.get(key)
            if hit is not None and hit[0] == st.st_mtime_ns \
                    and hit[1] == st.st_size:
                recs = hit[2]
                if recs and recs[0].get("type") == _METADATA:
                    return recs[0].get("payload", {})
                return {}
    if st is not None:
        with _parse_cache_lock:
            hit = _meta_cache.get(key)
            if hit is not None and hit[0] == st.st_mtime_ns:
                return hit[1]
    try:
        with open(path, encoding="utf-8") as f:
            first = f.readline().strip()
    except OSError:
        # Same scan-vs-close rename race as read_events: the metadata
        # line moved with the file — follow it.
        fin = _finished_sibling(path)
        if fin is not None and fin.exists():
            return job_metadata(fin)
        raise
    rec = json.loads(first) if first else {}
    meta = rec.get("payload", {}) if rec.get("type") == _METADATA else {}
    if st is not None:
        with _parse_cache_lock:
            if len(_meta_cache) >= _CACHE_MAX_FILES:
                _meta_cache.pop(next(iter(_meta_cache)))
            # mtime alone suffices: the metadata line is written once at
            # file creation and never rewritten.
            _meta_cache[key] = (st.st_mtime_ns, meta)
    return meta


def list_jobs(history_dir: str | Path) -> Iterator[Dict[str, Any]]:
    """All jobs under a history root, finished first then in-progress —
    the history server's scan (reference: HDFS scan in ParserUtils)."""
    root = Path(history_dir)
    for sub, suffix, state in (
            (constants.EVENTS_DIR_FINISHED, constants.JHIST_SUFFIX, "finished"),
            (constants.EVENTS_DIR_INTERMEDIATE,
             constants.JHIST_INPROGRESS_SUFFIX, "running")):
        d = root / sub
        if not d.is_dir():
            continue
        for p in sorted(d.iterdir()):
            if not p.name.endswith(suffix):
                continue
            app_id = p.name[:-len(suffix)]
            meta = job_metadata(p)
            yield {"app_id": app_id, "state": state, "path": str(p),
                   "metadata": meta}
