"""TonY-TPU: a TPU-native distributed-training orchestrator.

A brand-new framework with the capabilities of TonY (linkedin/TonY fork
claudiavmbrito/TonY): a client/CLI that packages and submits training jobs, an
application-master-style scheduler that gang-allocates TPU hosts as containers,
per-container task executors that wire framework rendezvous and launch user
code, a pluggable framework-runtime SPI (TF ``TF_CONFIG``, PyTorch DDP, a
Horovod-semantics adapter, and a first-class ``JAXRuntime`` driving
``jax.distributed.initialize`` and XLA collectives over ICI/DCN), heartbeat
failure detection with gang restart, an event-log-backed history server, and an
in-process "MiniPod" cluster for distributed tests without real hardware.

Reference parity map (upstream paths, see SURVEY.md; the reference mount was
empty so citations are upstream-relative, class-level):

==========================================  =========================================
Reference (Java)                            This package (Python/JAX)
==========================================  =========================================
tony-core TonyConfigurationKeys             tony_tpu.conf
tony-core TonySession / TonyTask            tony_tpu.session
tony-core rpc/* (Hadoop RPC + protobuf)     tony_tpu.rpc (JSON-lines TCP)
tony-core TaskExecutor / TaskMonitor        tony_tpu.executor
tony-core TonyApplicationMaster             tony_tpu.am
tony-core Framework SPI + runtime/*         tony_tpu.runtime
tony-core events/* (Avro jhist)             tony_tpu.events (JSONL jhist)
tony-core TonyClient                        tony_tpu.client
tony-core util/gpu/GpuDiscoverer            tony_tpu.discovery
tony-cli ClusterSubmitter                   tony_tpu.cli
tony-cli NotebookSubmitter                  tony_tpu.notebook
tony-azkaban TonyJob plugin                 tony_tpu.azkaban
tony-history-server (Play portal)           tony_tpu.history
tony-proxy ProxyServer                      tony_tpu.proxy
tony-mini (docker pseudo-cluster)           tony_tpu.minipod (in-process)
(delegated to ML frameworks in reference)   tony_tpu.models / ops / parallel / train
(user-side in reference)                    tony_tpu.distributed / ckpt
==========================================  =========================================
"""

__version__ = "0.3.0"
