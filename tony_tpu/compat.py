"""JAX API compatibility: one place that absorbs the moving surface.

The compute plane targets current JAX (``jax.shard_map``, ``jax.set_mesh``)
but must also run on the 0.4.x line some images pin (where manual sharding
lives in ``jax.experimental.shard_map`` and there is no ambient-mesh
context — ``NamedSharding`` carries its mesh explicitly, so the context is
simply not needed). Every module that manually shards goes through these
two helpers instead of probing ``jax`` itself.
"""

from __future__ import annotations

import contextlib
from typing import Any

import jax


def shard_map(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` with per-shard replication checking off — the
    schedules here build replication via explicit ``psum`` and assert it
    themselves (numerical pin tests), which the checker can't see."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def ambient_mesh_size() -> int:
    """Device count of the ambient abstract mesh (``jax.set_mesh`` scope),
    or 0 when none is set — including on 0.4.x, where no ambient-mesh
    concept exists (and :func:`mesh_context` is a no-op, so code gating on
    "am I inside the sharded train harness?" correctly sees 0 there)."""
    get = getattr(jax.sharding, "get_abstract_mesh", None)
    if get is None:
        return 0
    m = get()
    if m is None or m.empty:
        return 0
    return m.size


def mesh_context(mesh) -> Any:
    """Ambient-mesh scope for jitted GSPMD code: ``jax.set_mesh`` where it
    exists, a no-op otherwise (on 0.4.x the shardings baked into the jitted
    function are explicit ``NamedSharding``s, so no scope is required)."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return contextlib.nullcontext()
