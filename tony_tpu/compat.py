"""JAX API compatibility: one place that absorbs the moving surface.

The compute plane targets current JAX (``jax.shard_map``, ``jax.set_mesh``)
but must also run on the 0.4.x line some images pin (where manual sharding
lives in ``jax.experimental.shard_map`` and there is no ambient-mesh
context — ``NamedSharding`` carries its mesh explicitly, so the context is
simply not needed). Every module that manually shards goes through these
two helpers instead of probing ``jax`` itself.
"""

from __future__ import annotations

import contextlib
from typing import Any

import jax


def shard_map(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` with per-shard replication checking off — the
    schedules here build replication via explicit ``psum`` and assert it
    themselves (numerical pin tests), which the checker can't see."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def ambient_mesh_size() -> int:
    """Device count of the ambient abstract mesh (``jax.set_mesh`` scope),
    or 0 when none is set — including on 0.4.x, where no ambient-mesh
    concept exists (and :func:`mesh_context` is a no-op, so code gating on
    "am I inside the sharded train harness?" correctly sees 0 there)."""
    get = getattr(jax.sharding, "get_abstract_mesh", None)
    if get is None:
        return 0
    m = get()
    if m is None or m.empty:
        return 0
    return m.size


def mesh_context(mesh) -> Any:
    """Ambient-mesh scope for jitted GSPMD code: ``jax.set_mesh`` where it
    exists, a no-op otherwise (on 0.4.x the shardings baked into the jitted
    function are explicit ``NamedSharding``s, so no scope is required)."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return contextlib.nullcontext()


def serialize_compiled(compiled) -> Any:
    """``(payload_bytes, in_tree, out_tree)`` of a ``jax.stages.Compiled``
    via ``jax.experimental.serialize_executable`` — the AOT compile
    cache's wire (:mod:`tony_tpu.ckpt.aot`). Returns ``None`` when this
    jax/backend cannot serialize executables (older 0.4.x lines, or a
    PJRT plugin without executable serialization): the cache degrades to
    a counted miss, never a wrong program."""
    try:
        from jax.experimental import serialize_executable as _se
        return _se.serialize(compiled)
    except Exception:
        return None


def deserialize_compiled(payload: bytes, in_tree, out_tree) -> Any:
    """Load a serialized executable back into a callable
    ``jax.stages.Compiled`` — the other half of
    :func:`serialize_compiled`. ``None`` on ANY failure (version skew,
    plugin mismatch, torn payload): callers re-trace instead — a cold
    start may cost a compile, never a wrong program."""
    try:
        from jax.experimental import serialize_executable as _se
        return _se.deserialize_and_load(payload, in_tree, out_tree)
    except Exception:
        return None
