"""Container scheduling substrate: the YARN-RM/NM replacement (layer L0).

The reference delegates this layer entirely to Hadoop YARN (SURVEY.md §1 L0);
the AM asks the RM for containers sized ``{memory, vcores, gpus}`` and the NM
launches ``TaskExecutor`` JVMs. Here the same two verbs — allocate/launch and
reap — sit behind :class:`ContainerScheduler`, with two backends:

* :class:`LocalProcessScheduler` — containers are local subprocesses running
  ``python -m tony_tpu.executor``. This is both the MiniPod test substrate
  (the MiniYARNCluster analogue, SURVEY.md §4) and the single-host
  production path on one TPU-VM.
* :class:`TpuVmScheduler` — the multi-host pod-slice backend: same interface,
  launches executors on remote TPU-VM workers (one per host) over SSH.
  Resource semantics follow the ``yarn.io/tpu`` resource-type model from the
  north star: a request carries ``tpus`` and the scheduler places tasks so
  chip assignments never overlap (the JAXRuntime then pins
  ``TPU_VISIBLE_DEVICES`` per task).

Preemption is a first-class verb (``preempt``) because the reference's
failure machinery distinguishes preempted containers (re-request) from
crashed ones (fail-fast) — SURVEY.md §3.3.
"""

from __future__ import annotations

import os
import shlex
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from tony_tpu import constants
from tony_tpu import conf as conf_mod
from tony_tpu.util import child_pythonpath, control_plane_site_env


@dataclass
class ContainerLaunch:
    """One container ask: which task, with what env (reference: the
    ``ContainerLaunchContext`` the AM builds per matched allocation)."""
    job_type: str
    index: int
    env: Dict[str, str]
    memory_mb: int = 1024
    vcores: int = 1
    tpus: int = 0


@dataclass
class Container:
    """A granted container and its lifecycle (reference: YARN ``Container`` +
    completion status)."""
    container_id: str
    job_type: str
    index: int
    host: str
    exit_code: Optional[int] = None
    preempted: bool = False
    _proc: Optional[subprocess.Popen] = field(default=None, repr=False)

    @property
    def is_running(self) -> bool:
        return self.exit_code is None


class ContainerScheduler:
    """Substrate SPI: allocate-and-launch, reap, kill, preempt."""

    def launch(self, launch: ContainerLaunch) -> Container:
        raise NotImplementedError

    def poll_completed(self) -> List[Container]:
        """Containers that exited since the last poll (reference:
        ``onContainersCompleted``)."""
        raise NotImplementedError

    def stop_container(self, container: Container) -> None:
        raise NotImplementedError

    def preempt(self, container_id: str) -> bool:
        """Simulate/execute a scheduler preemption: the container dies and is
        reported with ``preempted=True`` (reference: YARN exit status
        ``PREEMPTED``). Returns False if the container is not running."""
        raise NotImplementedError

    def stop(self, drain_s: float = 5.0) -> None:
        """Tear down everything still running, then drain completions."""
        for c in self._live_containers():
            self.stop_container(c)
        deadline = time.monotonic() + drain_s
        while self._live_containers() and time.monotonic() < deadline:
            self.poll_completed()
            time.sleep(0.05)

    def _live_containers(self) -> List["Container"]:
        raise NotImplementedError


class LocalProcessScheduler(ContainerScheduler):
    """Containers as local subprocesses (MiniYARNCluster analogue).

    Each container gets a working directory ``<job_dir>/containers/<cid>``
    and its executor stdout/stderr tee into ``executor.log`` there. Resource
    numbers (memory/vcores) are recorded, not enforced — exactly like
    MiniYARNCluster's default; ``tpus`` asks are validated against
    ``total_tpus`` so over-subscription fails at launch, mirroring an RM
    rejecting an unsatisfiable resource ask.
    """

    def __init__(self, job_dir: str | Path, host: str = "127.0.0.1",
                 total_tpus: int = 0, conf=None):
        self.job_dir = Path(job_dir)
        self.host = host
        self.conf = conf                      # for docker command wrapping
        self.total_tpus = total_tpus          # 0 = unlimited (no TPU asks)
        self._tpus_in_use = 0
        self._lock = threading.Lock()
        self._running: Dict[str, Container] = {}
        self._next_id = 0

    def _new_cid(self) -> str:
        with self._lock:
            self._next_id += 1
            return f"container_{os.getpid()}_{self._next_id:04d}"

    def launch(self, launch: ContainerLaunch) -> Container:
        if self.total_tpus and launch.tpus:
            with self._lock:
                if self._tpus_in_use + launch.tpus > self.total_tpus:
                    raise RuntimeError(
                        f"unsatisfiable tpu ask: {launch.tpus} requested, "
                        f"{self.total_tpus - self._tpus_in_use} free")
                self._tpus_in_use += launch.tpus
        cid = self._new_cid()
        workdir = self.job_dir / "containers" / cid
        workdir.mkdir(parents=True, exist_ok=True)
        log = open(workdir / constants.EXECUTOR_LOG_NAME, "ab")
        # Curated task env (the YARN launch-context analogue): what the
        # executor needs, distinct from the host environ it also inherits
        # when running un-dockerized.
        task_env = dict(launch.env)
        task_env[constants.ENV_CONTAINER_ID] = cid
        task_env.setdefault(constants.ENV_LOG_DIR, str(workdir))
        task_env["TONY_EXECUTOR_HOST"] = self.host
        env = dict(os.environ)
        env.update(task_env)
        env["PYTHONPATH"] = child_pythonpath(env)
        task_env["PYTHONPATH"] = env["PYTHONPATH"]
        # -S: the executor is stdlib-only control plane; the USER process
        # it spawns runs plain python with the full site (jax plugins
        # registered normally). Site dirs for the executor's own lazy
        # imports travel via TONY_SITE_DIRS (util.restore_site_dirs) —
        # NOT for docker executors, whose tony_tpu lives in the IMAGE's
        # site-packages: they need the plain site import (host paths mean
        # nothing in the container).
        docker_on = self.conf is not None and self.conf.get_bool(
            conf_mod.DOCKER_ENABLED, False)
        if docker_on:
            argv = [sys.executable, "-m", "tony_tpu.executor"]
            argv = docker_wrap_command(self.conf, argv, env=task_env,
                                       workdir=str(workdir),
                                       mounts=[str(self.job_dir)])
        else:
            argv = [sys.executable, "-S", "-m", "tony_tpu.executor"]
            env.update(control_plane_site_env())
        proc = subprocess.Popen(
            argv, env=env, cwd=workdir, stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True)
        log.close()
        c = Container(container_id=cid, job_type=launch.job_type,
                      index=launch.index, host=self.host, _proc=proc)
        c._tpus = launch.tpus  # type: ignore[attr-defined]
        with self._lock:
            self._running[cid] = c
        return c

    def poll_completed(self) -> List[Container]:
        done = []
        with self._lock:
            for cid, c in list(self._running.items()):
                rc = c._proc.poll() if c._proc else -1
                if rc is not None:
                    c.exit_code = (constants.EXIT_PREEMPTED if c.preempted
                                   else rc)
                    self._tpus_in_use -= getattr(c, "_tpus", 0)
                    del self._running[cid]
                    done.append(c)
        return done

    def stop_container(self, container: Container) -> None:
        with self._lock:
            c = self._running.get(container.container_id)
        if c is not None and c._proc is not None and c._proc.poll() is None:
            # Kill the whole process group: executor + its user child.
            try:
                os.killpg(c._proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    def preempt(self, container_id: str) -> bool:
        with self._lock:
            c = self._running.get(container_id)
        if c is None or c._proc is None or c._proc.poll() is not None:
            return False
        c.preempted = True
        try:
            os.killpg(c._proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            return False
        return True

    def running(self) -> List[Container]:
        with self._lock:
            return list(self._running.values())

    _live_containers = running


def scheduler_from_conf(conf, job_dir: str | Path,
                        host: str = "127.0.0.1") -> ContainerScheduler:
    """Build the substrate the config names (reference: the RM is chosen by
    the cluster, not the job; here ``tony.scheduler.backend`` picks
    ``local`` (default) or ``tpu-vm``). ``tony.application.node-blacklist``
    hosts are excluded from placement — the reference's blacklist semantics
    applied at scheduler level."""
    from tony_tpu import conf as conf_mod
    backend = conf.get("tony.scheduler.backend", "local")
    blacklist = set(conf.get_list(conf_mod.APPLICATION_NODE_BLACKLIST))
    if backend == "tpu-vm":
        hosts = [h for h in conf.get_list("tony.scheduler.hosts")
                 if h not in blacklist]
        if not hosts:
            raise ValueError(
                "tony.scheduler.backend=tpu-vm needs tony.scheduler.hosts "
                "(after node-blacklist filtering)")
        return TpuVmScheduler(
            hosts,
            ssh_cmd=conf.get("tony.scheduler.ssh-command", "ssh"),
            remote_python=conf.get("tony.scheduler.remote-python", "python3"),
            remote_workdir=conf.get("tony.scheduler.remote-workdir",
                                    "/tmp/tony-tpu"),
            remote_pythonpath=conf.get("tony.scheduler.remote-pythonpath")
            or None,
            host_tpus=conf.get_int("tony.scheduler.host-tpus", 0))
    if backend != "local":
        raise ValueError(f"unknown tony.scheduler.backend={backend!r}")
    return None  # caller builds LocalProcessScheduler with its own args


def docker_wrap_command(conf, argv: List[str],
                        env: Optional[Dict[str, str]] = None,
                        workdir: Optional[str] = None,
                        mounts: Sequence[str] = ()) -> List[str]:
    """When ``tony.docker.enabled`` is set, wrap an executor launch command
    in ``docker run`` with the configured image (reference: the YARN docker
    runtime env ``YARN_CONTAINER_RUNTIME_TYPE=docker`` — SURVEY.md §2.1
    "Docker support"). Mirrors the YARN launch-context contract: the
    curated task ``env`` rides ``-e`` (not the host's full environ), each
    of ``mounts`` (the job dir, so conf/src/venv localization resolve) is
    bind-mounted at the same path, and ``workdir`` becomes the container
    cwd. The image must provide python + tony_tpu. Applied by
    ``LocalProcessScheduler.launch`` when it was constructed with the job
    config."""
    from tony_tpu import conf as conf_mod
    if not conf.get_bool(conf_mod.DOCKER_ENABLED, False):
        return argv
    image = conf.get(conf_mod.DOCKER_IMAGE, "")
    if not image:
        raise ValueError("tony.docker.enabled=true requires "
                         "tony.docker.containers.image")
    cmd = ["docker", "run", "--rm", "--network=host"]
    for m in mounts:
        cmd += ["-v", f"{m}:{m}"]
    if workdir:
        cmd += ["-w", str(workdir)]
    for key in sorted(env or ()):
        cmd += ["-e", f"{key}={env[key]}"]
    return cmd + [image] + argv


class TpuVmScheduler(ContainerScheduler):
    """Multi-host pod-slice backend: one executor per TPU-VM worker via SSH.

    The contract mirrors ``gcloud compute tpus tpu-vm ssh --worker=N
    --command`` fan-out: ``hosts`` lists worker addresses; the executor env
    rides the SSH command line; completion is detected by the remote shell
    exiting with the executor's code.

    Remote lifecycle: each launch runs the executor under ``setsid`` with
    its pid written to ``pids/<cid>.pid`` on the worker, so kill/preempt can
    reach the *remote process group* (executor + user child) over a second
    SSH exec — terminating only the local SSH client would orphan them.

    Placement: when ``host_tpus`` is set, each host carries that many chips
    and tasks are placed least-loaded-first so chip asks never oversubscribe
    a worker (the ``yarn.io/tpu`` resource-type semantics of the north
    star); with no chip asks, placement balances running task count.

    Exercised end-to-end by the fake-ssh e2e tier (``tests/test_e2e.py``):
    ``ssh_cmd`` pointed at a local shim script runs the full gang/failure/
    preemption matrix against this substrate without a pod.
    """

    def __init__(self, hosts: List[str], ssh_cmd: str = "ssh",
                 remote_python: str = "python3",
                 remote_workdir: str = "/tmp/tony-tpu",
                 remote_pythonpath: Optional[str] = None,
                 host_tpus: int = 0):
        if not hosts:
            raise ValueError("TpuVmScheduler requires at least one host")
        self.hosts = list(hosts)
        self.ssh_cmd = ssh_cmd
        self.remote_python = remote_python
        self.remote_workdir = remote_workdir
        self.remote_pythonpath = remote_pythonpath  # None = pip-installed
        self.host_tpus = host_tpus                  # chips per worker; 0 = off
        self._host_chips: Dict[str, int] = {h: 0 for h in self.hosts}
        self._host_tasks: Dict[str, int] = {h: 0 for h in self.hosts}
        self._running: Dict[str, Container] = {}
        self._lock = threading.Lock()
        self._stage_lock = threading.Lock()      # guards the lock table
        self._host_stage_locks: Dict[str, threading.Lock] = {}
        self._next_id = 0
        self._staged_hosts: set = set()

    def _ssh_argv(self, host: str, remote_sh: str) -> List[str]:
        """argv for one remote exec; ``ssh_cmd`` may carry flags
        (``ssh -i key``) or be a local shim script (tests)."""
        return shlex.split(self.ssh_cmd) + [host, remote_sh]

    def build_stage_command(self, local_dir: str, host: str,
                            remote_subdir: str, items: str = ".") -> str:
        """Shell pipeline staging a local dir (or named items within it)
        onto the worker (the HDFS localization analogue for the SSH
        substrate): tar stream over ssh — no temp files, one round trip."""
        dest = f"{self.remote_workdir}/{remote_subdir}"
        return (f"tar -C {shlex.quote(local_dir)} -cf - {items} | "
                f"{self.ssh_cmd} {host} "
                f"{shlex.quote(f'mkdir -p {dest} && tar -xf - -C {dest}')}")

    def build_remote_command(self, launch: ContainerLaunch, host: str,
                             cid: str = "adhoc") -> List[str]:
        """The SSH argv for one executor launch (separated for testability:
        command construction is covered by unit tests, the network is not).
        Paths in the env that point at client-side staging (conf, src,
        venv) are rewritten to the worker-side copies laid down by
        :meth:`build_stage_command`."""
        env = {**launch.env, "TONY_EXECUTOR_HOST": host}
        wd = self.remote_workdir
        if constants.ENV_CONF_PATH in env:
            env[constants.ENV_CONF_PATH] = (
                f"{wd}/conf/{constants.TONY_JOB_JSON}")
        if constants.ENV_SRC_DIR in env:
            env[constants.ENV_SRC_DIR] = f"{wd}/src"
        if constants.ENV_RESOURCES_DIR in env:
            env[constants.ENV_RESOURCES_DIR] = f"{wd}/resources"
        venv = env.get(constants.ENV_VENV)
        if venv:
            # Archives stage as the file itself; dirs stage as contents.
            if Path(venv).is_file():
                env[constants.ENV_VENV] = (
                    f"{wd}/venv-stage/{Path(venv).name}")
            else:
                env[constants.ENV_VENV] = f"{wd}/venv-stage"
        if self.remote_pythonpath:
            env["PYTHONPATH"] = self.remote_pythonpath
        # -S latency cut only when tony_tpu arrives via remote_pythonpath;
        # a pip-installed remote (remote_pythonpath=None) NEEDS the site
        # import to find tony_tpu at all. Remote site dirs are unknown
        # here, so no TONY_SITE_DIRS: the executor's lazy jax census falls
        # back to devfs/env — which is the real-TPU-host path anyway.
        interp_flags = " -S" if self.remote_pythonpath else ""
        exports = " ".join(
            f"export {k}={shlex.quote(v)};" for k, v in sorted(env.items()))
        # setsid: the executor becomes leader of a fresh process group whose
        # pgid == its pid, so `kill -- -$(cat pidfile)` reaps it AND the
        # user process it spawned; `wait` propagates the executor's exit
        # code (or 128+SIG after a remote kill) back through ssh.
        remote = (
            f"mkdir -p {wd}/pids && cd {wd} || exit 1; {exports} "
            f"setsid {self.remote_python}{interp_flags} -m tony_tpu.executor "
            f"< /dev/null & pid=$!; echo $pid > pids/{cid}.pid; "
            f"wait $pid; rc=$?; rm -f pids/{cid}.pid; exit $rc")
        return self._ssh_argv(host, remote)

    def _host_for(self, launch: ContainerLaunch) -> str:
        """Least-loaded placement with per-host chip accounting (reference:
        the RM matching a resource ask to a node with capacity)."""
        with self._lock:
            if launch.tpus and self.host_tpus:
                if launch.tpus > self.host_tpus:
                    raise RuntimeError(
                        f"unsatisfiable tpu ask: task wants {launch.tpus} "
                        f"chips but hosts have {self.host_tpus}")
                fits = [h for h in self.hosts
                        if self._host_chips[h] + launch.tpus <= self.host_tpus]
                if not fits:
                    raise RuntimeError(
                        f"unsatisfiable tpu ask: {launch.tpus} chips "
                        f"requested, per-host free: "
                        f"{ {h: self.host_tpus - self._host_chips[h] for h in self.hosts} }")
                host = min(fits, key=lambda h: (self._host_chips[h],
                                                self._host_tasks[h]))
                self._host_chips[host] += launch.tpus
            else:
                host = min(self.hosts, key=lambda h: self._host_tasks[h])
            self._host_tasks[host] += 1
        return host

    def _stage(self, local: str, host: str, subdir: str,
               items: str = ".") -> None:
        cmd = self.build_stage_command(local, host, subdir, items=items)
        proc = subprocess.run(cmd, shell=True, timeout=300,
                              capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"staging {local} -> {host}:{self.remote_workdir}/{subdir} "
                f"failed (rc={proc.returncode}): {proc.stderr[-500:]}")

    def _host_stage_lock(self, host: str) -> "threading.Lock":
        with self._stage_lock:
            return self._host_stage_locks.setdefault(host, threading.Lock())

    def _stage_once(self, launch: ContainerLaunch, host: str) -> None:
        """Stage conf + src + venv onto the worker the first time it's
        used. The host is marked staged only after every transfer succeeds;
        a failure raises so the launch (and the job) fails loudly instead
        of executors dying later on a missing-conf error. Serialized PER
        HOST (not globally): the AM launches a gang concurrently, and one
        global lock would make first-time staging to N hosts O(N) in
        transfer time — the exact latency the concurrent launches exist
        to remove."""
        with self._host_stage_lock(host):
            if host in self._staged_hosts:
                return
            conf_path = launch.env.get(constants.ENV_CONF_PATH)
            if conf_path and Path(conf_path).is_file():
                self._stage(str(Path(conf_path).parent), host, "conf",
                            items=Path(conf_path).name)
            src_dir = launch.env.get(constants.ENV_SRC_DIR)
            if src_dir and Path(src_dir).is_dir():
                self._stage(src_dir, host, "src")
            venv = launch.env.get(constants.ENV_VENV)
            if venv and Path(venv).is_file():
                self._stage(str(Path(venv).parent), host, "venv-stage",
                            items=Path(venv).name)
            elif venv and Path(venv).is_dir():
                self._stage(venv, host, "venv-stage")
            res_dir = launch.env.get(constants.ENV_RESOURCES_DIR)
            if res_dir and Path(res_dir).is_dir():
                self._stage(res_dir, host, "resources")
            self._staged_hosts.add(host)

    def launch(self, launch: ContainerLaunch) -> Container:
        host = self._host_for(launch)
        with self._lock:
            self._next_id += 1
            cid = f"container_tpuvm_{self._next_id:04d}"
        try:
            self._stage_once(launch, host)
            proc = subprocess.Popen(
                self.build_remote_command(launch, host, cid=cid),
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                start_new_session=True)
        except Exception:
            # Release the accounting or gang-restart retries would see the
            # chips as permanently occupied (the scheduler outlives attempts).
            self._release_host(host, launch.tpus)
            raise
        c = Container(container_id=cid, job_type=launch.job_type,
                      index=launch.index, host=host, _proc=proc)
        c._tpus = launch.tpus  # type: ignore[attr-defined]
        with self._lock:
            self._running[cid] = c
        return c

    def _release_host(self, host: str, tpus: int) -> None:
        with self._lock:
            if self.host_tpus and tpus:
                self._host_chips[host] -= tpus
            self._host_tasks[host] -= 1

    def poll_completed(self) -> List[Container]:
        done = []
        with self._lock:
            for cid, c in list(self._running.items()):
                rc = c._proc.poll() if c._proc else -1
                if rc is not None:
                    c.exit_code = (constants.EXIT_PREEMPTED if c.preempted
                                   else rc)
                    if self.host_tpus and getattr(c, "_tpus", 0):
                        self._host_chips[c.host] -= c._tpus
                    self._host_tasks[c.host] -= 1
                    del self._running[cid]
                    done.append(c)
        return done

    def _remote_kill(self, c: Container, sig: str = "KILL") -> bool:
        """Kill the remote executor's whole process group via its pidfile
        (second ssh exec). Returns True when the remote kill ran."""
        pidfile = f"{self.remote_workdir}/pids/{c.container_id}.pid"
        # `kill -s SIG -- -pgid`: the only group-kill spelling both dash
        # and bash builtins accept (`kill -SIG -- -pgid` is rejected by
        # dash, the default /bin/sh on debian-family TPU-VM images). The
        # pidfile is removed here, not only by the launch shell's cleanup —
        # the local ssh client may be torn down before that cleanup runs.
        sh = (f"[ -f {pidfile} ] && pid=$(cat {pidfile}) && "
              f"rm -f {pidfile} && kill -s {sig} -- -$pid 2>/dev/null")
        try:
            proc = subprocess.run(self._ssh_argv(c.host, sh), timeout=30,
                                  stdout=subprocess.DEVNULL,
                                  stderr=subprocess.DEVNULL)
            return proc.returncode == 0
        except (subprocess.TimeoutExpired, OSError):
            return False

    def stop_container(self, container: Container) -> None:
        with self._lock:
            c = self._running.get(container.container_id)
        if c is not None and c._proc is not None and c._proc.poll() is None:
            if not self._remote_kill(c):
                # Remote side unreachable (or already gone): at least drop
                # the local ssh client so the AM's teardown completes.
                try:
                    c._proc.terminate()
                except OSError:
                    pass

    def preempt(self, container_id: str) -> bool:
        with self._lock:
            c = self._running.get(container_id)
        if c is None or c._proc is None or c._proc.poll() is not None:
            return False
        c.preempted = True
        if not self._remote_kill(c):
            c._proc.kill()
        return True

    def _live_containers(self) -> List[Container]:
        with self._lock:
            return list(self._running.values())

    def stop(self, drain_s: float = 10.0) -> None:
        super().stop(drain_s)
