"""The analyzer's rule suite: every invariant the training stack claims by
construction, re-checked against the traced program.

The rules run over (a) the step's closed jaxpr, (b) the planner artifacts
that made the claims (:class:`~tony_tpu.parallel.overlap.GradBuckets`,
:class:`~tony_tpu.parallel.sched.GatherPlan`, the shared
:func:`~tony_tpu.parallel.overlap.reduce_schedule`), and (c) the traced
function's donation metadata. Findings are structured records — rule,
kind, severity, message, equation provenance, byte cost — so the CI gate
can diff them and the waiver mechanism can address them individually.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.analysis import jaxprwalk as jw
from tony_tpu.parallel import FSDP

# Collectives at/below this payload are bookkeeping scalars (loss/aux
# means, grad-norm psums) — enumerated but auto-accepted by the audit, so
# the planned set stays about the transfers that cost bandwidth.
SCALAR_NBYTES = 256

RULE_NAMES: Tuple[str, ...] = (
    "replication_leak", "collective_audit", "dtype_policy", "donation",
    "signature")


@dataclass(frozen=True)
class Finding:
    """One rule violation (or acceptance-worthy observation)."""

    rule: str          # one of RULE_NAMES
    kind: str          # specific finding kind within the rule
    severity: str      # "error" | "warning"
    message: str
    provenance: str = ""
    nbytes: int = 0
    waived: bool = False
    waived_by: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "kind": self.kind,
                "severity": self.severity, "message": self.message,
                "provenance": self.provenance, "nbytes": self.nbytes,
                "waived": self.waived, "waived_by": self.waived_by}


@dataclass(frozen=True)
class Waiver:
    """Accept a known finding: matches when ``rule`` equals the finding's
    rule (or ``"*"``) and ``match`` is a substring of its message or
    provenance. ``reason`` is recorded on the waived finding — a waiver
    without a reason is a suppression, not an acceptance."""

    rule: str
    match: str
    reason: str


def apply_waivers(findings: Sequence[Finding], waivers: Sequence[Waiver]
                  ) -> Tuple[List[Finding], List[Finding]]:
    """Split ``findings`` into (active, waived)."""
    active: List[Finding] = []
    waived: List[Finding] = []
    for f in findings:
        hit = next(
            (w for w in waivers
             if w.rule in ("*", f.rule)
             and (w.match in f.message or w.match in f.provenance)),
            None)
        if hit is None:
            active.append(f)
        else:
            waived.append(replace(f, waived=True, waived_by=hit.reason))
    return active, waived


# ---------------------------------------------------------------------------
# The planned-collective set (rules 1 + 2 audit the jaxpr against this)
# ---------------------------------------------------------------------------

@dataclass
class Expected:
    """One planned collective-equation shape: ``count`` static equation
    occurrences of ``kind`` over ``axes`` moving ``nbytes`` each."""

    kind: str
    axes: frozenset
    nbytes: int
    count: int
    plane: str
    note: str = ""


def _add(exp: List[Expected], kind: str, axes: Sequence[str], nbytes: int,
         plane: str, note: str = "") -> None:
    key = frozenset(axes)
    for e in exp:
        if (e.kind, e.axes, e.nbytes, e.plane) == (kind, key, nbytes,
                                                   plane):
            e.count += 1
            return
    exp.append(Expected(kind, key, int(nbytes), 1, plane, note))


def expected_accum_collectives(plan: Any, gplan: Optional[Any], mesh: Any,
                               *, gather: str = "bucketed",
                               reduce_op: str = "all_reduce",
                               hierarchy: str = "auto",
                               update: str = "optax",
                               fused: Optional[Any] = None,
                               quant: bool = False
                               ) -> List[Expected]:
    """The full planned-collective multiset of one
    ``make_accum_train_step`` trace, derived from the SAME planner
    artifacts the engine executes (``reduce_schedule`` is shared code, so
    the audit can't drift from the step): forward gathers (bucketed or
    per-leaf — int8-sized when the quantized lane is on: 1 B/element on
    the wire, the scalar amax ``pmax``es ride under the auto-accept
    threshold), the per-bucket reduce schedule with its post-scatter psum
    groups, the tail re-gathers, and — for the fused-optimizer path — the
    update plane's own param re-gathers."""
    from tony_tpu.parallel import overlap

    exp: List[Expected] = []
    zero3 = gplan is not None and plan.shard_size > 1
    if zero3:
        if gather == "bucketed":
            for b in gplan.gather_buckets:
                nb = plan.bucket_numel[b] if quant \
                    else plan.bucket_nbytes[b]
                _add(exp, "all_gather", (gplan.axis,),
                     nb, "fwd_gather", f"bucket {b}")
        else:
            for i, _d in gplan.gather_leaves:
                nb = int(np.prod(plan.shapes[i], dtype=np.int64)) \
                    * plan.dtypes[i].itemsize
                _add(exp, "all_gather", (gplan.axis,), nb, "fwd_gather",
                     f"leaf {i}")
    sched, rs_axes, rs_group, hier = overlap.reduce_schedule(
        plan, mesh, reduce_op=reduce_op, hierarchy=hierarchy)
    axes = overlap.sync_axes(mesh)
    for b, (mode, post) in enumerate(sched):
        nb = plan.bucket_nbytes[b]
        item = plan.dtypes[plan.buckets[b][0]].itemsize
        if mode == "scatter":
            chunk = nb // plan.shard_size
            _add(exp, "reduce_scatter", (FSDP,), chunk, "grad_reduce",
                 f"bucket {b}")
            for g in post:
                _add(exp, "psum", g, chunk, "grad_reduce",
                     f"bucket {b} post")
        elif mode == "rs":
            numel = plan.bucket_numel[b]
            padded = numel + ((-numel) % rs_group)
            chunk = (padded // rs_group) * item
            _add(exp, "reduce_scatter", rs_axes, chunk, "grad_reduce",
                 f"bucket {b}")
            for g in post:
                _add(exp, "psum", g, chunk, "grad_reduce",
                     f"bucket {b} post")
            # Both the optax tail and the fused tail re-gather "rs"
            # buckets once (their leaves live replicated).
            _add(exp, "all_gather", rs_axes, padded * item, "grad_reduce",
                 f"bucket {b} tail re-gather")
        else:
            _add(exp, "psum", axes, nb, "grad_reduce", f"bucket {b}")
    for b in range(plan.n_buckets):
        if plan._is_scatter(b) and plan._is_padded(b) \
                and update != "fused_bucket":
            # Padded (uneven-leaf) scatter buckets re-gather over fsdp
            # after the scan so their grads exit whole.
            _add(exp, "all_gather", (FSDP,), plan.bucket_nbytes[b],
                 "grad_reduce", f"bucket {b} padded tail re-gather")
    if update == "fused_bucket" and fused is not None:
        for kind, caxes, nb, note in fused.region_collectives(
                plan, sharded=zero3):
            _add(exp, kind, caxes, nb, "param_update", note)
    return exp


# ---------------------------------------------------------------------------
# Rules 1 + 2: replication-leak + collective audit
# ---------------------------------------------------------------------------

def reconcile_collectives(collectives: Sequence[jw.CollectiveEqn],
                          expected: Sequence[Expected], *,
                          scalar_nbytes: int = SCALAR_NBYTES
                          ) -> List[Finding]:
    """Match every collective equation against the planned multiset.

    Unmatched big equations become findings: an ``all_gather`` over the
    fsdp axis is a **replication leak** (it materializes a full
    fsdp-sharded buffer the prefetch window never planned — the ZeRO-3
    memory contract breaks exactly here); anything else is an **unplanned
    collective** (the GSPMD partitioner or a model edit inserted traffic
    the scheduler doesn't own). Planned-but-missing entries above the
    scalar threshold are reported too — a silently vanished collective
    usually means the audit is looking at a stale plan."""
    findings: List[Finding] = []
    pool = [Expected(e.kind, e.axes, e.nbytes, e.count, e.plane, e.note)
            for e in expected]
    for c in collectives:
        hit = next(
            (e for e in pool
             if e.count > 0 and e.kind == c.kind
             and e.axes == frozenset(c.axes) and e.nbytes == c.nbytes),
            None)
        if hit is not None:
            hit.count -= 1
            continue
        if c.nbytes <= scalar_nbytes:
            continue                      # bookkeeping scalar — accepted
        if c.kind == "all_gather" and FSDP in c.axes:
            findings.append(Finding(
                rule="replication_leak", kind="unplanned_gather",
                severity="error",
                message=(f"all_gather over {list(c.axes)} materializes "
                         f"{c.nbytes} B of fsdp-sharded state outside "
                         f"the planned prefetch live window"),
                provenance=c.provenance, nbytes=c.nbytes))
        else:
            findings.append(Finding(
                rule="collective_audit", kind="unplanned_collective",
                severity="error",
                message=(f"{c.kind} over {list(c.axes)} moving "
                         f"{c.nbytes} B is not in the planner's "
                         f"collective set (GSPMD-inserted reshard or "
                         f"unregistered plane?)"),
                provenance=c.provenance, nbytes=c.nbytes))
    for e in pool:
        if e.count > 0 and e.nbytes > scalar_nbytes:
            findings.append(Finding(
                rule="collective_audit", kind="planned_missing",
                severity="error",
                message=(f"planned {e.kind} over {sorted(e.axes)} "
                         f"({e.nbytes} B x{e.count}, plane {e.plane}"
                         f"{', ' + e.note if e.note else ''}) never "
                         f"appears in the traced step — stale plan or "
                         f"dropped collective"),
                nbytes=e.nbytes * e.count))
    return findings


def check_prefetch_chain(closed: Any, gplan: Optional[Any],
                         gather: str) -> List[Finding]:
    """Rule 1's structural half: a bucketed gather plan with
    ``prefetch > 0`` promises bucket *k* waits on bucket *k − prefetch*
    via an ``optimization_barrier`` chain. If the barriers are gone (a
    refactor dropped them), every gather may hoist to step start and the
    whole replicated working set materializes at once — exactly the leak
    the window bounds."""
    if gplan is None or gather != "bucketed" or not gplan.prefetch:
        return []
    need = max(0, gplan.n_gather_buckets - gplan.prefetch)
    if not need:
        return []
    have = jw.prim_counts(closed).get("optimization_barrier", 0)
    if have >= need:
        return []
    return [Finding(
        rule="replication_leak", kind="prefetch_chain_broken",
        severity="error",
        message=(f"gather plan promises a prefetch={gplan.prefetch} "
                 f"barrier chain over {gplan.n_gather_buckets} buckets "
                 f"({need} optimization_barrier eqns) but the trace has "
                 f"{have} — gathers can hoist past the live window "
                 f"(window {gplan.window_nbytes()} B, total "
                 f"{sum(gplan.gather_nbytes)} B)"),
        nbytes=sum(gplan.gather_nbytes))]


# ---------------------------------------------------------------------------
# Rule 3: dtype policy
# ---------------------------------------------------------------------------

# Equations that ACCUMULATE: a low-precision output here loses gradient
# mass silently (bf16 has 8 mantissa bits; summing K terms loses ~log2 K
# of them). Matmuls are deliberately absent — bf16 on the MXU with f32
# accumulation is the intended fast path.
_REDUCTION_PRIMS = ("reduce_sum", "psum", "reduce_scatter", "add_any",
                    "cumsum")
_LOW_PRECISION = (jnp.bfloat16, jnp.float16)
# Integer carries narrower than int32 SATURATE instead of losing
# mantissa: an int8-carried psum wraps/clips at the second operand. The
# quantized lane ships int8 only through non-accumulating collectives
# (all_gather) and accumulates every dot in int32 — that pair is the
# blessed pattern; everything else is a finding.
_NARROW_INT = (jnp.int8, jnp.int16, jnp.uint8, jnp.uint16)


def dtype_findings(closed: Any) -> List[Finding]:
    """f64 must never appear (a silent promotion doubles every byte count
    the planner budgeted); bf16/f16 must never be the carry dtype of a
    reduction and int8/int16 must never be one either (they saturate);
    an int8×int8 ``dot_general`` must accumulate wide
    (``preferred_element_type=int32`` — the quantized lane's blessed
    int8→int32-with-f32-rescale pattern passes untouched)."""
    out: List[Finding] = []
    for path, i, eqn in jw.iter_eqns(closed):
        prov = ""
        if eqn.primitive.name == "dot_general":
            in_dts = [getattr(getattr(v, "aval", None), "dtype", None)
                      for v in eqn.invars]
            out_dt = getattr(getattr(eqn.outvars[0], "aval", None),
                             "dtype", None)
            if (len(in_dts) == 2 and out_dt is not None
                    and all(dt is not None and any(dt == nd for nd in
                                                   _NARROW_INT)
                            for dt in in_dts)
                    and any(out_dt == nd for nd in _NARROW_INT)):
                out.append(Finding(
                    rule="dtype_policy", kind="narrow_int_accumulation",
                    severity="error",
                    message=(f"dot_general over "
                             f"{np.dtype(in_dts[0]).name} operands "
                             f"accumulates in {np.dtype(out_dt).name} — "
                             f"int8 matmuls must accumulate wide "
                             f"(preferred_element_type=int32, the "
                             f"quantized lane's blessed pattern)"),
                    provenance=jw.CollectiveEqn(
                        eqn.primitive.name, (), jw.eqn_out_nbytes(eqn),
                        path, i, jw.source_of(eqn)).provenance,
                    nbytes=jw.eqn_out_nbytes(eqn)))
        for v in eqn.outvars:
            dt = getattr(getattr(v, "aval", None), "dtype", None)
            if dt is None:
                continue
            if dt == jnp.float64:
                prov = prov or jw.CollectiveEqn(
                    eqn.primitive.name, (), jw.eqn_out_nbytes(eqn), path,
                    i, jw.source_of(eqn)).provenance
                out.append(Finding(
                    rule="dtype_policy", kind="f64_promotion",
                    severity="error",
                    message=(f"{eqn.primitive.name} produces float64 — "
                             f"silent f64 promotion doubles bandwidth "
                             f"and memory against every plan"),
                    provenance=prov, nbytes=jw.eqn_out_nbytes(eqn)))
                break
        if eqn.primitive.name in _REDUCTION_PRIMS:
            for v in eqn.outvars:
                dt = getattr(getattr(v, "aval", None), "dtype", None)
                if dt is None:
                    continue
                low = any(dt == lp for lp in _LOW_PRECISION)
                narrow = any(dt == nd for nd in _NARROW_INT)
                if low or narrow:
                    why = "reductions must carry f32 (bf16 never " \
                          "accumulates)" if low else \
                          "narrow integer reductions saturate — carry " \
                          "int32/f32 (int8 rides only non-accumulating " \
                          "collectives like the quantized gather)"
                    out.append(Finding(
                        rule="dtype_policy",
                        kind="low_precision_reduction" if low
                        else "int_carried_reduction",
                        severity="error",
                        message=(f"{eqn.primitive.name} accumulates in "
                                 f"{np.dtype(dt).name} — {why}"),
                        provenance=jw.CollectiveEqn(
                            eqn.primitive.name, jw.eqn_axes(eqn),
                            jw.eqn_out_nbytes(eqn), path, i,
                            jw.source_of(eqn)).provenance,
                        nbytes=jw.eqn_out_nbytes(eqn)))
                    break
    return out


def opt_state_findings(state: Any) -> List[Finding]:
    """The fused plane's bucket-resident moment slots must be f32 — the
    whole point of keeping our own slots instead of optax's
    param-dtype-following moments (bf16 params would otherwise get bf16
    Adam variance, which underflows at small grads)."""
    from tony_tpu.ops import fused_optim

    out: List[Finding] = []
    if not fused_optim.is_fused_state(state):
        return out
    for name, bufs in state.opt_state.get("slots", {}).items():
        for b, buf in enumerate(bufs):
            dt = getattr(buf, "dtype", None)
            if dt is not None and dt != jnp.float32:
                out.append(Finding(
                    rule="dtype_policy", kind="non_f32_moments",
                    severity="error",
                    message=(f"moment slot {name!r} bucket {b} is "
                             f"{np.dtype(dt).name}, policy requires "
                             f"float32"),
                    provenance=f"opt_state.slots[{name!r}][{b}]",
                    nbytes=jw.aval_nbytes(buf)))
    return out


# ---------------------------------------------------------------------------
# Rule 4: donation
# ---------------------------------------------------------------------------

def donation_findings(traced: Any, args: Sequence[Any],
                      arg_names: Sequence[str],
                      expect_donated: Sequence[int] = (0,)
                      ) -> List[Finding]:
    """Every argument in ``expect_donated`` (the state: params, bucket
    accumulator seeds, opt-state slots) must be donated to the jit — an
    undonated state doubles its residency, because XLA cannot alias the
    update into the input buffers. The finding names the argument and
    its byte cost, biggest leaf first."""
    donated = tuple(getattr(traced, "donate_argnums", ()) or ())
    out: List[Finding] = []
    for argnum in expect_donated:
        if argnum in donated or argnum >= len(args):
            continue
        flat = jax.tree_util.tree_flatten_with_path(args[argnum])[0]
        sized = sorted(((jw.aval_nbytes(leaf), path)
                        for path, leaf in flat), reverse=True,
                       key=lambda t: t[0])
        total = sum(nb for nb, _ in sized)
        top = ", ".join(
            f"{jax.tree_util.keystr(path)}={nb}B"
            for nb, path in sized[:3])
        name = arg_names[argnum] if argnum < len(arg_names) \
            else f"arg{argnum}"
        out.append(Finding(
            rule="donation", kind="undonated_argument", severity="error",
            message=(f"argument {argnum} ({name!r}, {total} B) is not "
                     f"donated — XLA cannot alias the updated state into "
                     f"its input buffers (largest leaves: {top})"),
            provenance=f"donate_argnums={donated}", nbytes=total))
    return out
