"""Source lint for the jax-0.4 GSPMD concat footgun (``make lint``).

The bug this machine-checks: jax 0.4.x's partitioner mis-reshards
concatenated slices of sharded arrays on multi-axis meshes (measured 2×
values in PR 7 — Adam's scale invariance masked it for a whole bench
round). The stack's rule since then: ``jnp.concatenate``/``jnp.stack``
over potentially-sharded inputs happens ONLY at the approved
region-local pack sites (``parallel/overlap.py``, ``ops/fused_optim.py``,
the ``ckpt`` codec) or through host numpy. A static check can't see
shardings, so the lint is conservative: every ``jnp.concatenate`` /
``jnp.stack`` call outside the approved files is flagged unless the call
line — or the contiguous comment block immediately above it — carries
the audit pragma::

    # packsite: region-local — <why this site is safe>

Host ``np.concatenate`` is never flagged — that IS the sanctioned detour.

Run directly (``python -m tony_tpu.analysis.srclint [paths...]``) or via
``make lint`` / ``tony analyze --lint``.
"""

from __future__ import annotations

import ast
import sys
from dataclasses import dataclass
from pathlib import Path
from typing import List, Sequence, Tuple

PRAGMA = "packsite: region-local"

# Whole files whose packing IS the approved implementation (the planner's
# shard-major pack, the fused plane's local pack, the ckpt codec).
ALLOWED_FILES: Tuple[str, ...] = ("parallel/overlap.py",
                                  "ops/fused_optim.py")
ALLOWED_DIRS: Tuple[str, ...] = ("ckpt/",)

_BANNED_ATTRS = ("concatenate", "stack")


@dataclass(frozen=True)
class LintViolation:
    path: str
    line: int
    col: int
    call: str

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.call} "
                f"outside an approved pack site — jax-0.4 GSPMD "
                f"mis-reshards concatenated slices of sharded arrays on "
                f"multi-axis meshes; pack region-locally (inside the "
                f"shard_map region) or via host numpy, or bless an "
                f"audited site with '# {PRAGMA} — <why>'")


def _is_jnp_call(node: ast.Call) -> str:
    """``"jnp.concatenate"``-style name when the call is a banned jax
    numpy op, else ``""``. Matches ``jnp.<op>`` and ``jax.numpy.<op>``
    (the two spellings the codebase uses); host ``np.<op>`` passes."""
    func = node.func
    if not isinstance(func, ast.Attribute) or func.attr not in _BANNED_ATTRS:
        return ""
    recv = func.value
    if isinstance(recv, ast.Name) and recv.id == "jnp":
        return f"jnp.{func.attr}"
    if isinstance(recv, ast.Attribute) and recv.attr == "numpy" \
            and isinstance(recv.value, ast.Name) and recv.value.id == "jax":
        return f"jax.numpy.{func.attr}"
    return ""


def _allowed(rel: str) -> bool:
    rel = rel.replace("\\", "/")
    return rel in ALLOWED_FILES or any(rel.startswith(d)
                                       for d in ALLOWED_DIRS)


def lint_source(src: str, rel: str, display_path: str
                ) -> List[LintViolation]:
    """Lint one file's source text (``rel`` is the package-relative path
    the allowlist matches against)."""
    if _allowed(rel):
        return []
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [LintViolation(display_path, e.lineno or 0, 0,
                              "unparseable file")]
    lines = src.splitlines()
    out: List[LintViolation] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        call = _is_jnp_call(node)
        if not call:
            continue
        # Blessed when the pragma sits on the call's own line(s) or in
        # the CONTIGUOUS comment block immediately above it. Anchoring at
        # the call matters: a window of N lines below the pragma would
        # let an unaudited call stacked right after an audited one pass.
        start = node.lineno - 1
        end = min(len(lines), getattr(node, "end_lineno", node.lineno))
        blessed = any(PRAGMA in lines[i] for i in range(start, end))
        i = start - 1
        while not blessed and i >= 0 and lines[i].lstrip().startswith("#"):
            blessed = PRAGMA in lines[i]
            i -= 1
        if not blessed:
            out.append(LintViolation(display_path, node.lineno,
                                     node.col_offset, call))
    return out


def _package_rel(path: Path, root: Path) -> str:
    """The path the allowlist matches against: relative to the nearest
    enclosing ``tony_tpu`` package dir, however the linter was invoked
    (whole tree, a subdirectory, or one explicit file) — else relative
    to ``root`` (temp trees in tests)."""
    parts = path.resolve().parts
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] == "tony_tpu":
            return "/".join(parts[i + 1:])
    try:
        return str(path.resolve().relative_to(root.resolve()))
    except ValueError:
        return path.name


def lint_file(path: Path, root: Path) -> List[LintViolation]:
    return lint_source(path.read_text(), _package_rel(path, root),
                       str(path))


def default_root() -> Path:
    """The installed ``tony_tpu`` package directory."""
    return Path(__file__).resolve().parents[1]


def lint_tree(root: Path) -> List[LintViolation]:
    """Lint every ``.py`` under ``root`` (a ``tony_tpu`` package dir)."""
    root = Path(root)
    out: List[LintViolation] = []
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        out.extend(lint_file(path, root))
    return out


def main(argv: Sequence[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    roots = [Path(a) for a in argv] or [default_root()]
    violations: List[LintViolation] = []
    for r in roots:
        if not r.exists():
            # A typo'd/misrooted path must fail the gate, not silently
            # lint nothing and report clean.
            print(f"srclint: path does not exist: {r}")
            return 2
        violations.extend(lint_file(r, r.parent) if r.is_file()
                          else lint_tree(r))
    for v in violations:
        print(v)
    if violations:
        print(f"srclint: {len(violations)} violation(s)")
        return 1
    print("srclint: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
