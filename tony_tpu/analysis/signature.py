"""Step-signature snapshots: a stable digest of a traced step's program
shape, committed as JSON so structural drift shows up as a reviewable
diff instead of a silent regression.

The digest is everything the perf story rests on and nothing that churns
per run: recursive equation count, the full primitive histogram, the
collective census (count per kind + total payload bytes), the
optimization-barrier count (the prefetch chain), and the donation-aware
live-buffer high-water estimate. All of it is a pure function of the
jaxpr, so two traces of the same code on the same jax pin produce
byte-identical digests — structural claims of the BENCH_r10 kind
("3467 → 890 eqns") become pin-able as committed files. The shipped pins
under ``tests/signatures/`` cover the canonical ``tony analyze`` configs
(the small mnist-mlp harness geometry, e.g. 305 eqns for the fused
step), not the bench-sized tree.

Regenerating after an INTENDED change: run with ``TONY_UPDATE_SIGNATURES=1``
(or ``tony analyze --signatures tests/signatures --update-signatures``)
and commit the new files.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

UPDATE_ENV = "TONY_UPDATE_SIGNATURES"


def _update_requested() -> bool:
    """Explicitly-false spellings must NOT regenerate: a CI config
    setting ``TONY_UPDATE_SIGNATURES=0`` to disable updates would
    otherwise silently rewrite every pin and pass the drift gate."""
    return os.environ.get(UPDATE_ENV, "").strip().lower() \
        not in ("", "0", "false", "no")


def step_signature(closed: Any,
                   donated: Optional[Sequence[bool]] = None, *,
                   collectives: Optional[Sequence[Any]] = None
                   ) -> Dict[str, Any]:
    """The digest of one closed jaxpr (see module docstring).
    ``collectives`` reuses an already-collected census (the analyze
    entries walk the program for rule 2 anyway)."""
    from tony_tpu.analysis import jaxprwalk as jw  # lazy: jax-backed

    prims = jw.prim_counts(closed)
    colls = jw.collect_collectives(closed) if collectives is None \
        else list(collectives)
    by_kind: Dict[str, int] = {}
    for c in colls:
        by_kind[c.kind] = by_kind.get(c.kind, 0) + 1
    return {
        "eqns": sum(prims.values()),
        "prims": prims,
        "collectives": dict(sorted(by_kind.items())),
        "collective_nbytes": sum(c.nbytes for c in colls),
        "optimization_barriers": prims.get("optimization_barrier", 0),
        "live_high_water_nbytes": jw.live_high_water(closed, donated),
    }


def diff_signature(pinned: Dict[str, Any], current: Dict[str, Any]
                   ) -> List[str]:
    """Human-readable drift lines, empty when identical. Nested dicts
    (prims, collectives) diff per key so a review sees "scan: 1 -> 2",
    not two opaque blobs."""
    lines: List[str] = []
    for key in sorted(set(pinned) | set(current)):
        a, b = pinned.get(key), current.get(key)
        if a == b:
            continue
        if isinstance(a, dict) and isinstance(b, dict):
            for k in sorted(set(a) | set(b)):
                if a.get(k) != b.get(k):
                    lines.append(f"{key}.{k}: {a.get(k, 0)} -> "
                                 f"{b.get(k, 0)}")
        else:
            lines.append(f"{key}: {a} -> {b}")
    return lines


def load_signature(path: str | Path) -> Optional[Dict[str, Any]]:
    p = Path(path)
    if not p.is_file():
        return None
    return json.loads(p.read_text())


def save_signature(path: str | Path, sig: Dict[str, Any]) -> None:
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(sig, indent=2, sort_keys=True) + "\n")


def check_signature(sig: Dict[str, Any], path: str | Path) -> List[str]:
    """Compare ``sig`` against the committed pin at ``path``.

    Returns drift lines (empty = match). With ``TONY_UPDATE_SIGNATURES=1``
    the pin is rewritten instead and the check passes — the diff then
    lives in git, where it belongs. A missing pin file is reported as
    drift (the snapshot must be committed, not lazily created by CI)."""
    if _update_requested():
        save_signature(path, sig)
        return []
    pinned = load_signature(path)
    if pinned is None:
        return [f"no committed signature at {path} — run with "
                f"{UPDATE_ENV}=1 and commit the file"]
    return diff_signature(pinned, sig)
