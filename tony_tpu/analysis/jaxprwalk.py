"""Closed-jaxpr walking primitives for the static analyzer: recursive
equation enumeration with provenance, collective-equation extraction, and
a donation-aware live-buffer high-water estimate.

Everything here reads ONLY the jaxpr (shapes, dtypes, primitive params,
source info) — no compilation, no execution — so the analyzer runs in
milliseconds on CPU against exactly the program the step will trace on
TPU. The walk recurses through every sub-jaxpr a primitive carries
(``pjit``/``scan``/``shard_map``/``cond``/``while``/``remat``/custom-AD
calls), because the collectives the rules care about live two levels down:
``jit → scan body → shard_map body``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np
from jax import core as jcore

# The manual-collective primitive names on the jax 0.4.x line
# (``jax.lax.psum_scatter`` binds the ``reduce_scatter`` primitive).
COLLECTIVE_PRIMS: Tuple[str, ...] = (
    "psum", "all_gather", "reduce_scatter", "all_to_all", "ppermute",
    "pmax", "pmin", "pgather")

# Primitive params that carry sub-jaxprs worth descending into. Secondary
# AD thunks (``jvp_jaxpr_fun``, ``fwd``/``bwd`` wrappers) are NOT jaxpr
# values on this jax line, so the natural type check below skips them —
# no equation is counted twice.
_SUB_KEYS: Tuple[str, ...] = (
    "jaxpr", "call_jaxpr", "body_jaxpr", "cond_jaxpr", "branches",
    "fun_jaxpr")


def _as_closed(v: Any) -> Optional[jcore.ClosedJaxpr]:
    if isinstance(v, jcore.ClosedJaxpr):
        return v
    if isinstance(v, jcore.Jaxpr):
        return jcore.ClosedJaxpr(v, ())
    return None


def subjaxprs(eqn: jcore.JaxprEqn) -> List[Tuple[str, jcore.ClosedJaxpr]]:
    """``(param_key, closed_jaxpr)`` for every sub-jaxpr of one equation."""
    out: List[Tuple[str, jcore.ClosedJaxpr]] = []
    for key in _SUB_KEYS:
        v = eqn.params.get(key)
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for item in vals:
            closed = _as_closed(item)
            if closed is not None:
                out.append((key, closed))
    return out


def iter_eqns(closed: jcore.ClosedJaxpr, path: str = ""
              ) -> Iterator[Tuple[str, int, jcore.JaxprEqn]]:
    """Depth-first ``(path, index, eqn)`` over the whole program; ``path``
    names the enclosing primitives (``"scan/shard_map"``), ``index`` the
    equation's position within its own jaxpr."""
    for i, eqn in enumerate(closed.jaxpr.eqns):
        yield path, i, eqn
        for _key, sub in subjaxprs(eqn):
            inner = f"{path}/{eqn.primitive.name}" if path \
                else eqn.primitive.name
            yield from iter_eqns(sub, inner)


def count_eqns(closed: jcore.ClosedJaxpr) -> int:
    return sum(1 for _ in iter_eqns(closed))


def prim_counts(closed: jcore.ClosedJaxpr) -> Dict[str, int]:
    """Recursive primitive histogram (static equation occurrences — a
    scan body counts once, not once per trip)."""
    out: Dict[str, int] = {}
    for _p, _i, eqn in iter_eqns(closed):
        name = eqn.primitive.name
        out[name] = out.get(name, 0) + 1
    return dict(sorted(out.items()))


def aval_nbytes(aval: Any) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


def eqn_out_nbytes(eqn: jcore.JaxprEqn) -> int:
    return sum(aval_nbytes(v.aval) for v in eqn.outvars)


def eqn_axes(eqn: jcore.JaxprEqn) -> Tuple[str, ...]:
    """The mesh axes a collective equation runs over (``psum`` carries
    ``axes``, the rest ``axis_name``; either may be a bare string)."""
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(axes, str):
        return (axes,)
    return tuple(a for a in tuple(axes) if isinstance(a, str))


def source_of(eqn: jcore.JaxprEqn) -> str:
    """``file:line (fn)`` of the frame that issued the equation — the
    provenance half every finding carries."""
    try:
        from jax._src import source_info_util
        return source_info_util.summarize(eqn.source_info)
    except Exception:  # noqa: BLE001 — provenance is best-effort
        return "<unknown>"


@dataclass(frozen=True)
class CollectiveEqn:
    """One collective equation, with enough provenance to act on."""

    kind: str                  # primitive name (psum/all_gather/...)
    axes: Tuple[str, ...]      # mesh axes it runs over
    nbytes: int                # summed output payload bytes
    path: str                  # enclosing-primitive path ("scan/shard_map")
    index: int                 # equation index within its jaxpr
    src: str                   # issuing source line

    @property
    def provenance(self) -> str:
        where = f"{self.path}[{self.index}]" if self.path \
            else f"[{self.index}]"
        return (f"{where} {self.kind} over {list(self.axes)} "
                f"{self.nbytes} B @ {self.src}")


def collect_collectives(closed: jcore.ClosedJaxpr) -> List[CollectiveEqn]:
    """Every collective equation in the program, in program order."""
    out: List[CollectiveEqn] = []
    for path, i, eqn in iter_eqns(closed):
        if eqn.primitive.name in COLLECTIVE_PRIMS:
            out.append(CollectiveEqn(
                kind=eqn.primitive.name, axes=eqn_axes(eqn),
                nbytes=eqn_out_nbytes(eqn), path=path, index=i,
                src=source_of(eqn)))
    return out


def _internal_high_water(closed: jcore.ClosedJaxpr) -> int:
    """High-water bytes of values DEFINED inside this jaxpr (its invars
    and constvars are the caller's buffers — counted at the call site,
    not here)."""
    return _high_water(closed.jaxpr, free_invars=True)


def _high_water(jaxpr: jcore.Jaxpr, *, free_invars: bool,
                donated: Optional[Sequence[bool]] = None) -> int:
    eqns = jaxpr.eqns
    last_use: Dict[Any, int] = {}
    for t, eqn in enumerate(eqns):
        for v in eqn.invars:
            if isinstance(v, jcore.Var):
                last_use[v] = t
    end = len(eqns)
    for v in jaxpr.outvars:
        if isinstance(v, jcore.Var):
            last_use[v] = end               # outputs live to the end

    alive: Dict[Any, int] = {}
    if not free_invars:
        # Program inputs: a donated buffer frees at its last use (XLA may
        # alias it); everything else is the caller's and stays resident
        # for the whole execution.
        flags = list(donated) if donated is not None else []
        flags += [False] * (len(jaxpr.invars) - len(flags))
        for v, don in zip(jaxpr.invars, flags):
            if not don:
                last_use[v] = end
            alive[v] = aval_nbytes(v.aval)
        for v in jaxpr.constvars:
            last_use[v] = end
            alive[v] = aval_nbytes(v.aval)
    high = sum(alive.values())
    for t, eqn in enumerate(eqns):
        base = sum(alive.values())
        # A sub-jaxpr's internal temporaries peak while the caller's live
        # set persists around the call.
        for _key, sub in subjaxprs(eqn):
            high = max(high, base + _internal_high_water(sub))
        for v in eqn.outvars:
            if isinstance(v, jcore.Var):
                alive[v] = aval_nbytes(v.aval)
        high = max(high, sum(alive.values()))
        for v in list(alive):
            if last_use.get(v, -1) <= t:
                del alive[v]
    return high


def live_high_water(closed: jcore.ClosedJaxpr,
                    donated: Optional[Sequence[bool]] = None) -> int:
    """Donation-aware live-buffer high-water ESTIMATE in bytes: a linear
    liveness scan over the equation list (sub-jaxprs contribute their
    internal peak at their call site). It ignores XLA fusion and
    rematerialization, so it is an upper-ish bound useful for regression
    pinning and for measuring what donation buys — not an allocator
    prediction. ``donated`` flags the program's flat invars."""
    return _high_water(closed.jaxpr, free_invars=False, donated=donated)
