"""Concurrency analysis plane: lock discipline, lock order, thread hygiene.

PRs 10-13 made the host side of the stack genuinely multi-threaded —
admission queues, the refcounted/COW paged pool, router failover
dispatch, heartbeat publishers, prefetch and snapshot daemons — while
``tony analyze`` still audited only the *traced* program. This module is
the third pillar next to the jaxpr rules and :mod:`srclint`, covering
the host-side concurrency that now carries production traffic. Three
passes, all jax-free (AST + :mod:`threading` only), so ``make lint``
stays runnable on a gateway host:

1. **Lock discipline** — per class, infer which ``self.*`` attributes
   are *guarded* (mutated inside a ``with self.<lock>:`` block anywhere
   in the class, where ``<lock>`` is an attribute assigned a
   ``threading.Lock``/``RLock``/``Condition``) and flag mutations of a
   guarded attribute outside any lock: the classic lost-update drift
   where one new call site forgets the lock the rest of the class
   holds. Reads are deliberately NOT flagged — single-field telemetry
   reads are benign under the GIL and flagging them would bury the real
   findings; the lint targets torn read-modify-write. A flagged site is
   blessed with an audited pragma (mirroring ``# packsite:``)::

       # lockfree: <why this unlocked mutation is safe>

   A pragma with no reason is itself a finding — a blessing without an
   audit is a suppression.

2. **Lock order** — a static graph of nested ``with self.<lock>:``
   acquisitions across every module, merged with the edges a runtime
   *lock witness* observed (:class:`WitnessLock` — an instrumented
   Lock/RLock/Condition shim recording per-thread acquisition chains
   into the profiler's ``lock_report()`` registry). Cycle detection
   over the merged graph turns a potential deadlock into a NAMED
   finding with the full cycle and the first-observation sites — not a
   hung CI job.

3. **Thread hygiene** — every ``threading.Thread(...)`` construction
   must be ``daemon=True`` or be assigned to a binding that is
   ``.join()``-ed in its owning scope (``self._t`` joined anywhere in
   the class; a local joined in the same function). A non-daemon,
   never-joined thread outlives its owner silently; a daemon thread
   that is never joined dies mid-write at interpreter exit — the audit
   makes the choice explicit. Blessed with ``# threadlife: <reason>``.

Findings diff against a committed baseline
(``tests/signatures/concurrency.json``) so the gate is "no NEW
findings", reviewable like the step-signature pins. Run directly
(``python -m tony_tpu.analysis.concurrency [paths] [--baseline f]``),
via ``make lint``, or as ``tony analyze --concurrency``.
"""

from __future__ import annotations

import ast
import json
import sys
import threading
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tony_tpu._trace import trace_record
# One definition of package-relative display paths and the default lint
# root for BOTH source lints (jax-free like this module) — baseline
# fingerprints and srclint's allowlist must never disagree on what a
# path looks like.
from tony_tpu.analysis.srclint import _package_rel, default_root

LOCKFREE_PRAGMA = "lockfree:"
THREADLIFE_PRAGMA = "threadlife:"

RULE_NAMES: Tuple[str, ...] = ("lock_discipline", "lock_order",
                               "thread_hygiene")

# Attribute assigned one of these constructors => a lock attribute.
_LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition"})

# Method names that mutate their receiver in place: a call
# ``self.X.append(...)`` counts as a mutation of ``self.X``. Queue
# put/get are excluded — queue.Queue carries its own lock.
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "popitem", "clear", "update", "add", "discard",
    "setdefault", "sort", "reverse", "move_to_end",
})


@dataclass(frozen=True)
class ConcFinding:
    """One concurrency finding. ``provenance`` is the stable anchor
    (``Class.attr`` / the lock cycle / the thread binding) and —
    together with rule, kind, and file — the baseline fingerprint, so
    unrelated line churn never invalidates a blessing."""

    rule: str          # one of RULE_NAMES
    kind: str          # specific finding kind within the rule
    message: str
    path: str = ""
    line: int = 0
    provenance: str = ""
    blessed: bool = False
    blessed_by: str = ""

    def fingerprint(self) -> str:
        return f"{self.rule}:{self.kind}:{self.path}:{self.provenance}"

    def to_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "kind": self.kind,
                "message": self.message, "path": self.path,
                "line": self.line, "provenance": self.provenance,
                "blessed": self.blessed, "blessed_by": self.blessed_by}

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}/{self.kind}] "
                f"{self.message}")


# ---------------------------------------------------------------------------
# Pragma anchoring (same contract as srclint: the node's own line(s) or
# the CONTIGUOUS comment block immediately above it — a pragma can never
# bless a later statement).
# ---------------------------------------------------------------------------

def _pragma_reason(lines: Sequence[str], node: ast.AST,
                   pragma: str) -> Optional[str]:
    """The pragma's reason text when present at ``node`` (its own lines
    or the contiguous comment block above); ``""`` when the pragma is
    present but bare; ``None`` when absent."""
    def _scan(line: str) -> Optional[str]:
        i = line.find("#")
        while i >= 0:
            tail = line[i + 1:].strip()
            if tail.startswith(pragma):
                return tail[len(pragma):].strip()
            i = line.find("#", i + 1)
        return None

    start = node.lineno - 1
    end = min(len(lines), getattr(node, "end_lineno", node.lineno))
    for i in range(start, end):
        r = _scan(lines[i])
        if r is not None:
            return r
    i = start - 1
    while i >= 0 and lines[i].lstrip().startswith("#"):
        r = _scan(lines[i])
        if r is not None:
            return r
        i -= 1
    return None


def _bless(findings: List[ConcFinding], f: ConcFinding,
           reason: Optional[str]) -> None:
    """File ``f`` according to its pragma state: absent -> active;
    bare -> an active ``bare_pragma`` finding (a blessing without a
    reason is a suppression); reasoned -> blessed."""
    from dataclasses import replace

    if reason is None:
        findings.append(f)
    elif not reason:
        findings.append(replace(
            f, kind="bare_pragma",
            message=f"pragma carries no reason at a finding it blesses "
                    f"({f.kind}: {f.message})"))
    else:
        findings.append(replace(f, blessed=True, blessed_by=reason))


# ---------------------------------------------------------------------------
# Pass 1 + 2 (static): lock discipline and the static lock-order graph
# ---------------------------------------------------------------------------

def _self_attr(node: ast.AST) -> Optional[str]:
    """``X`` when ``node`` is exactly ``self.X``, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attributes of ``cls`` assigned a threading.Lock/RLock/Condition
    anywhere in the class body (``self.X = threading.Lock()``)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        func = node.value.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")
        if name not in _LOCK_FACTORIES:
            continue
        for tgt in node.targets:
            attr = _self_attr(tgt)
            if attr is not None:
                out.add(attr)
    return out


def _mutation_targets(node: ast.AST) -> List[Tuple[str, str]]:
    """``(attr, how)`` for every DIRECT ``self.X`` mutation this single
    node performs (no recursion): plain/aug/ann assignment, subscript
    store ``self.X[k] = v``, ``del self.X[...]``, and in-place mutator
    calls ``self.X.append(...)``. Mutations through a longer chain
    (``self.X.y[k] = v``) mutate the inner object, not the attribute
    binding, and are out of scope for an attribute-guard lint."""
    out: List[Tuple[str, str]] = []

    def _target(tgt: ast.AST, how: str) -> None:
        attr = _self_attr(tgt)
        if attr is not None:
            out.append((attr, how))
            return
        if isinstance(tgt, ast.Subscript):
            attr = _self_attr(tgt.value)
            if attr is not None:
                out.append((attr, f"{how}[]"))
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                _target(el, how)

    if isinstance(node, ast.Assign):
        for tgt in node.targets:
            _target(tgt, "write")
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        if getattr(node, "value", None) is not None or \
                isinstance(node, ast.AugAssign):
            _target(node.target, "write")
    elif isinstance(node, ast.Delete):
        for tgt in node.targets:
            _target(tgt, "del")
    elif isinstance(node, ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
            attr = _self_attr(func.value)
            if attr is not None:
                out.append((attr, f".{func.attr}()"))
    return out


@dataclass
class _ClassScan:
    """One class's lock-discipline evidence."""
    qual: str                                   # e.g. "serve/engine.py:ServeEngine"
    lock_attrs: Set[str]
    # attr -> first (lock, line) that guarded a mutation of it
    guarded: Dict[str, Tuple[str, int]]
    # (attr, how, line, node, method) mutations performed while NO lock
    # is held — the method rides into the finding's provenance so a
    # baseline blessing covers ONE audited site's method, not every
    # future unlocked mutation of the attribute anywhere in the class.
    bare: List[Tuple[str, str, int, ast.AST, str]]
    # static acquisition-order edges (outer, inner, "path:line")
    edges: List[Tuple[str, str, str]]


def _scan_class(cls: ast.ClassDef, rel: str,
                lock_attrs: Optional[Set[str]] = None) -> _ClassScan:
    # No early-out on empty lock_attrs: a class may guard exclusively
    # through helper-fetched locks (``with self._part_lock(key):``),
    # which the walk below still recognizes. Callers pass the
    # inheritance-merged set (same-file bases) so a subclass's
    # ``with self._lock:`` over a base-declared lock records real holds.
    if lock_attrs is None:
        lock_attrs = _lock_attrs(cls)
    scan = _ClassScan(qual=f"{rel}:{cls.name}", lock_attrs=lock_attrs,
                      guarded={}, bare=[], edges=[])

    def walk(node: ast.AST, held: Tuple[str, ...], meth: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # A nested function's body runs later — usually on another
            # thread or after the with-block exited — so the lexically
            # enclosing lock is NOT held when it executes.
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in body:
                walk(child, (), meth)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in node.items:
                walk(item.context_expr, held + tuple(acquired), meth)
                attr = _self_attr(item.context_expr)
                if attr not in lock_attrs:
                    # ``with self._host_stage_lock(host):`` — a lock
                    # fetched through a helper whose name says so. The
                    # pseudo-name keeps per-key lock tables inside the
                    # discipline/order passes.
                    attr = None
                    if isinstance(item.context_expr, ast.Call):
                        fattr = _self_attr(item.context_expr.func)
                        if fattr is not None and "lock" in fattr.lower():
                            attr = f"{fattr}()"
                if attr is not None:
                    for h in held + tuple(acquired):
                        if h != attr:
                            scan.edges.append(
                                (f"{cls.name}.{h}", f"{cls.name}.{attr}",
                                 f"{rel}:{node.lineno}"))
                    acquired.append(attr)
            for child in node.body:
                walk(child, held + tuple(acquired), meth)
            return
        for attr, how in _mutation_targets(node):
            if attr in lock_attrs:
                continue
            if held:
                scan.guarded.setdefault(attr, (held[-1], node.lineno))
            else:
                scan.bare.append((attr, how, node.lineno, node, meth))
        for child in ast.iter_child_nodes(node):
            walk(child, held, meth)

    for stmt in cls.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # Construction runs before any concurrency exists: __init__ (and
        # the _init_* helpers it delegates to) neither witnesses a guard
        # nor violates one. The underscore-terminated prefix is the
        # whole exemption — a runtime `_initialize_stats()` must NOT
        # slip through as "construction".
        if stmt.name == "__init__" or stmt.name.startswith("_init_"):
            continue
        for child in stmt.body:
            walk(child, (), stmt.name)
    return scan


def lint_source(src: str, rel: str, display_path: str
                ) -> Tuple[List[ConcFinding], List[Tuple[str, str, str]]]:
    """Lock-discipline + thread-hygiene lint of one file's source text;
    returns ``(findings, static lock-order edges)``. Findings carry
    their pragma state resolved (``blessed``/``blessed_by``)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [ConcFinding("lock_discipline", "unparseable",
                            "unparseable file", display_path,
                            e.lineno or 0)], []
    lines = src.splitlines()
    findings: List[ConcFinding] = []
    edges: List[Tuple[str, str, str]] = []
    # Inheritance, same-file: a subclass's lock attrs and guard evidence
    # include its in-file base chain's, so SpecEngine-style hierarchies
    # (subclass methods touching base-guarded state) stay covered. A
    # base defined in ANOTHER module is out of a single-file lint's
    # reach — keep thread-shared mutations in the module that owns the
    # lock, or the discipline pass cannot see the guard.
    classes = [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]
    by_name = {c.name: c for c in classes}

    def base_chain(c: ast.ClassDef,
                   seen: Tuple[str, ...] = ()) -> List[ast.ClassDef]:
        out: List[ast.ClassDef] = []
        for b in c.bases:
            if isinstance(b, ast.Name) and b.id in by_name \
                    and b.id not in seen and b.id != c.name:
                base = by_name[b.id]
                out.append(base)
                out.extend(base_chain(base, seen + (c.name, b.id)))
        return out

    chains = {c.name: base_chain(c) for c in classes}
    merged_locks = {
        c.name: set().union(_lock_attrs(c),
                            *[_lock_attrs(b) for b in chains[c.name]])
        for c in classes}
    scans = {c.name: _scan_class(c, rel, lock_attrs=merged_locks[c.name])
             for c in classes}
    for node in classes:
        scan = scans[node.name]
        edges.extend(scan.edges)
        guarded: Dict[str, Tuple[str, int]] = dict(scan.guarded)
        for base in chains[node.name]:
            for attr, ev in scans[base.name].guarded.items():
                guarded.setdefault(attr, ev)
        for attr, how, line, anchor, meth in scan.bare:
            if attr not in guarded:
                continue
            lock, gline = guarded[attr]
            f = ConcFinding(
                "lock_discipline", "unguarded_write",
                f"{node.name}.{attr} is mutated ({how}) in {meth}() "
                f"outside any "
                f"lock, but is guarded by {node.name}.{lock} elsewhere "
                f"(e.g. line {gline}) — a torn read-modify-write loses "
                f"updates; hold the lock or bless with "
                f"'# {LOCKFREE_PRAGMA} <why>'",
                display_path, line, f"{node.name}.{meth}.{attr}")
            _bless(findings, f, _pragma_reason(lines, anchor,
                                               LOCKFREE_PRAGMA))
    findings.extend(_thread_hygiene(tree, lines, display_path))
    return findings, edges


# ---------------------------------------------------------------------------
# Pass 3 (static): thread hygiene
# ---------------------------------------------------------------------------

def _is_thread_ctor(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr == "Thread" and \
            isinstance(func.value, ast.Name) and \
            func.value.id == "threading"
    return isinstance(func, ast.Name) and func.id == "Thread"


def _joined_self_attrs(scope: ast.AST) -> Set[str]:
    """``X`` for every ``self.X.join(...)`` call anywhere in ``scope``."""
    out: Set[str] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join":
            attr = _self_attr(node.func.value)
            if attr is not None:
                out.add(attr)
    return out


def _joined_names(scope: ast.AST) -> Set[str]:
    """``x`` for every ``x.join(...)`` call anywhere in ``scope``."""
    out: Set[str] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join" and \
                isinstance(node.func.value, ast.Name):
            out.add(node.func.value.id)
    return out


def _thread_hygiene(tree: ast.Module, lines: Sequence[str],
                    display_path: str) -> List[ConcFinding]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    findings: List[ConcFinding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
            continue
        daemon = next((kw.value for kw in node.keywords
                       if kw.arg == "daemon"), None)
        if isinstance(daemon, ast.Constant) and daemon.value is True:
            continue
        # Ownership: the nearest Assign whose value is this call.
        parent = parents.get(node)
        target: Optional[ast.AST] = None
        if isinstance(parent, ast.Assign) and parent.value is node \
                and len(parent.targets) == 1:
            target = parent.targets[0]
        # Enclosing scopes, innermost first.
        scopes: List[ast.AST] = []
        p: Optional[ast.AST] = node
        while p is not None:
            p = parents.get(p)
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                scopes.append(p)
        attr = _self_attr(target) if target is not None else None
        binding = "<unassigned>"
        joined = False
        if attr is not None:
            binding = f"self.{attr}"
            owner = next((s for s in scopes
                          if isinstance(s, ast.ClassDef)), tree)
            joined = attr in _joined_self_attrs(owner)
        elif isinstance(target, ast.Name):
            binding = target.id
            owner = next((s for s in scopes
                          if isinstance(s, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))), tree)
            joined = target.id in _joined_names(owner)
        if joined:
            continue
        scope_name = ".".join(s.name for s in reversed(scopes)) or \
            "<module>"
        detail = ("daemon is not a literal True"
                  if daemon is not None else "non-daemon")
        f = ConcFinding(
            "thread_hygiene", "unjoined_thread",
            f"threading.Thread bound to {binding} in {scope_name} is "
            f"{detail} and never .join()-ed in its owning scope — it "
            f"outlives teardown silently; make it daemon=True, join it "
            f"on a shutdown path, or bless with "
            f"'# {THREADLIFE_PRAGMA} <why>'",
            display_path, node.lineno, f"{scope_name}.{binding}")
        _bless(findings, f, _pragma_reason(lines, node,
                                           THREADLIFE_PRAGMA))
    return findings


# ---------------------------------------------------------------------------
# The runtime lock witness
# ---------------------------------------------------------------------------

_tls = threading.local()


def _held_stack() -> List[str]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = []
        _tls.stack = st
    return st


class _WitnessGraph:
    """Process-global observed lock-order graph. New edges bank a fresh
    snapshot into ``tony_tpu.profiler.lock_report()`` (registry
    ``"locks"``, tag ``"witness"``) — banking only on NEW edges keeps
    the steady-state acquire path to one dict hit under this lock."""

    def __init__(self) -> None:
        self._lock = threading.Lock()       # guards _edges/_locks
        self._edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._locks: Set[str] = set()

    def register(self, name: str) -> None:
        with self._lock:
            self._locks.add(name)

    def add_edge(self, src: str, dst: str) -> None:
        tname = threading.current_thread().name
        with self._lock:
            entry = self._edges.get((src, dst))
            fresh = entry is None
            if fresh:
                entry = {"count": 0, "threads": set(),
                         "where": _caller_site()}
                self._edges[(src, dst)] = entry
            entry["count"] += 1
            entry["threads"].add(tname)
        if fresh:
            self.bank()

    def edges(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"src": s, "dst": d, "count": e["count"],
                     "threads": sorted(e["threads"]),
                     "where": e["where"]}
                    for (s, d), e in sorted(self._edges.items())]

    def locks(self) -> List[str]:
        with self._lock:
            return sorted(self._locks)

    def reset(self) -> None:
        with self._lock:
            self._edges.clear()
            self._locks.clear()
        self.bank()

    def bank(self, tag: str = "witness") -> None:
        trace_record("locks", tag, locks=self.locks(),
                     edges=self.edges())


def _caller_site() -> str:
    """First stack frame outside this module — the acquisition site an
    inversion finding names."""
    f = sys._getframe(1)
    while f is not None and f.f_globals.get("__name__") == __name__:
        f = f.f_back
    return f"{f.f_code.co_filename}:{f.f_lineno}" if f is not None else ""


_GRAPH = _WitnessGraph()


def _on_acquire(name: str) -> None:
    st = _held_stack()
    for held in dict.fromkeys(st):
        if held != name:
            _GRAPH.add_edge(held, name)
    st.append(name)


def _on_release(name: str) -> None:
    st = _held_stack()
    for i in range(len(st) - 1, -1, -1):   # non-LIFO release tolerated
        if st[i] == name:
            del st[i]
            return


class WitnessLock:
    """Drop-in ``threading.Lock``/``RLock`` recording per-thread
    acquisition chains into the process-global witness graph. Re-entrant
    holds never self-edge; contention is unchanged (the real lock does
    the blocking, bookkeeping happens after acquisition succeeds)."""

    def __init__(self, name: str, _factory: Any = threading.Lock):
        self.name = str(name)
        self._lk = _factory()
        _GRAPH.register(self.name)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            _on_acquire(self.name)
        return ok

    def release(self) -> None:
        self._lk.release()
        _on_release(self.name)

    def __enter__(self) -> "WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc: object) -> None:
        self.release()


def Lock(name: str) -> WitnessLock:
    """An instrumented ``threading.Lock``."""
    return WitnessLock(name, threading.Lock)


def RLock(name: str) -> WitnessLock:
    """An instrumented ``threading.RLock``."""
    return WitnessLock(name, threading.RLock)


class WitnessCondition:
    """Instrumented ``threading.Condition`` over a :class:`WitnessLock`:
    ``wait()`` releases the witness hold for its sleep (exactly like the
    real lock) so a waiter's chain never fabricates an edge across the
    wait."""

    def __init__(self, name: str, lock: Optional[WitnessLock] = None):
        self._wl = lock if lock is not None else WitnessLock(
            name, threading.RLock)
        self.name = self._wl.name
        self._cond = threading.Condition(self._wl._lk)

    def acquire(self, *a: Any, **kw: Any) -> bool:
        return self._wl.acquire(*a, **kw)

    def release(self) -> None:
        self._wl.release()

    def __enter__(self) -> "WitnessCondition":
        self.acquire()
        return self

    def __exit__(self, *exc: object) -> None:
        self.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        _on_release(self.name)
        try:
            return self._cond.wait(timeout)
        finally:
            _on_acquire(self.name)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


def Condition(name: str,
              lock: Optional[WitnessLock] = None) -> WitnessCondition:
    """An instrumented ``threading.Condition``."""
    return WitnessCondition(name, lock)


def observed_edges() -> List[Dict[str, Any]]:
    """The witness's observed acquisition-order edges (src held when dst
    was acquired), with counts, thread names, first-observation site."""
    return _GRAPH.edges()


def reset_witness() -> None:
    """Clear the observed graph (tests; a fresh scenario)."""
    _GRAPH.reset()


def bank_witness(tag: str = "witness") -> None:
    """Bank the current observed graph into
    ``tony_tpu.profiler.lock_report()`` under ``tag``."""
    _GRAPH.bank(tag)


# ---------------------------------------------------------------------------
# Cycle detection over the merged static + observed graph
# ---------------------------------------------------------------------------

def find_cycles(edges: Iterable[Tuple[str, str]]) -> List[List[str]]:
    """Simple cycles in the directed graph, each as a closed node path
    ``[a, b, ..., a]``, deduplicated up to rotation. DFS with a path
    stack — lock graphs are tiny, exhaustiveness beats cleverness."""
    adj: Dict[str, List[str]] = {}
    for s, d in edges:
        if d not in adj.setdefault(s, []):
            adj[s].append(d)
        adj.setdefault(d, [])
    seen: Set[Tuple[str, ...]] = set()
    cycles: List[List[str]] = []

    def dfs(node: str, path: List[str], on_path: Set[str]) -> None:
        for nxt in adj.get(node, ()):
            if nxt in on_path:
                i = path.index(nxt)
                cyc = path[i:]
                j = cyc.index(min(cyc))
                key = tuple(cyc[j:] + cyc[:j])
                if key not in seen:
                    seen.add(key)
                    cycles.append(list(key) + [key[0]])
                continue
            path.append(nxt)
            on_path.add(nxt)
            dfs(nxt, path, on_path)
            on_path.discard(nxt)
            path.pop()

    for start in sorted(adj):
        dfs(start, [start], {start})
    return cycles


def check_lock_order(
        static_edges: Sequence[Tuple[str, str, str]] = (),
        observed: Optional[Sequence[Dict[str, Any]]] = None
) -> List[ConcFinding]:
    """Merge the static graph with the witness's observed edges (default:
    the live process-global graph) and return one ``lock_order``
    finding per cycle — a potential deadlock, NAMED, with the
    acquisition sites that contributed each edge."""
    if observed is None:
        observed = observed_edges()
    merged: List[Tuple[str, str]] = []
    origin: Dict[Tuple[str, str], List[str]] = {}
    for s, d, where in static_edges:
        merged.append((s, d))
        origin.setdefault((s, d), []).append(f"static {where}")
    for e in observed:
        key = (e["src"], e["dst"])
        merged.append(key)
        origin.setdefault(key, []).append(
            f"witness {e.get('where', '')} "
            f"(x{e.get('count', 1)}, threads "
            f"{','.join(e.get('threads', []))})")
    findings: List[ConcFinding] = []
    for cyc in find_cycles(merged):
        pairs = list(zip(cyc, cyc[1:]))
        prov = " -> ".join(cyc)
        sites = "; ".join(f"{a}->{b}: {origin[(a, b)][0]}"
                          for a, b in pairs)
        findings.append(ConcFinding(
            "lock_order", "inversion",
            f"potential deadlock: lock-order cycle {prov} ({sites})",
            provenance=prov))
    return findings


# ---------------------------------------------------------------------------
# Baseline (the committed blessings file under tests/signatures/)
# ---------------------------------------------------------------------------

def load_baseline(path: str | Path) -> Dict[str, str]:
    """``fingerprint -> reason`` from the committed baseline; missing
    file means an empty baseline (zero pre-blessed findings)."""
    p = Path(path)
    if not p.is_file():
        return {}
    data = json.loads(p.read_text())
    return {e["fingerprint"]: e.get("reason", "")
            for e in data.get("blessed", [])}


def write_baseline(path: str | Path, findings: Sequence[ConcFinding],
                   reason: str = "baselined at HEAD",
                   existing: Optional[Dict[str, str]] = None) -> None:
    """Rewrite the baseline to bless exactly the CURRENTLY-FIRING
    findings that are not pragma-blessed (pass the findings BEFORE
    :func:`apply_baseline` — pragma state resolved, baseline not yet
    applied), keeping the audited reason of every fingerprint already in
    ``existing`` — a regen adds the new and prunes the stale but never
    silently un-blesses (or re-words) a still-firing audited finding."""
    existing = existing or {}
    entries: Dict[str, str] = {}
    for f in findings:
        if f.blessed:                     # pragma-blessed: no entry needed
            continue
        fp = f.fingerprint()
        entries.setdefault(fp, existing.get(fp, reason))
    Path(path).write_text(json.dumps(
        {"blessed": [{"fingerprint": fp, "reason": entries[fp]}
                     for fp in sorted(entries)]},
        indent=2, sort_keys=True) + "\n")


def apply_baseline(findings: Sequence[ConcFinding],
                   baseline: Dict[str, str]
                   ) -> Tuple[List[ConcFinding], List[ConcFinding]]:
    """Split into (active, blessed): pragma-blessed findings and
    baseline-fingerprint matches land in the second list."""
    from dataclasses import replace

    active: List[ConcFinding] = []
    blessed: List[ConcFinding] = []
    for f in findings:
        if f.blessed:
            blessed.append(f)
        elif f.fingerprint() in baseline:
            blessed.append(replace(
                f, blessed=True, blessed_by=baseline[f.fingerprint()]))
        else:
            active.append(f)
    return active, blessed


# ---------------------------------------------------------------------------
# Tree entry points (mirror srclint's)
# ---------------------------------------------------------------------------



def analyze_tree(root: str | Path
                 ) -> Tuple[List[ConcFinding],
                            List[Tuple[str, str, str]]]:
    """Lint every ``.py`` under ``root``; returns ``(findings, static
    lock-order edges)`` with pragma state resolved per finding."""
    root = Path(root)
    findings: List[ConcFinding] = []
    edges: List[Tuple[str, str, str]] = []
    paths = [root] if root.is_file() else sorted(root.rglob("*.py"))
    for path in paths:
        if "__pycache__" in path.parts:
            continue
        fs, es = lint_source(path.read_text(),
                             _package_rel(path, root), str(path))
        findings.extend(fs)
        edges.extend(es)
    return findings, edges


@dataclass
class ConcReport:
    """One concurrency-analysis run over a tree. ``observed`` is the
    witness-edge set the cycle check actually consumed — the summary and
    the banked record count THAT, not whatever the live global graph
    holds at print time."""
    findings: List[ConcFinding]          # active (unblessed) only
    blessed: List[ConcFinding]
    static_edges: List[Tuple[str, str, str]]
    observed: List[Dict[str, Any]]

    @property
    def ok(self) -> bool:
        return not self.findings

    def summary(self) -> str:
        state = "CLEAN" if self.ok else f"{len(self.findings)} finding(s)"
        return (f"[concurrency] {state} ({len(self.blessed)} blessed, "
                f"{len(self.static_edges)} static lock-order edge(s), "
                f"{len(self.observed)} witnessed)")


def analyze_concurrency(root: Optional[str | Path] = None,
                        baseline_path: Optional[str | Path] = None,
                        include_witness: bool = True) -> ConcReport:
    """The full pass: discipline + hygiene lint over ``root`` (default:
    the installed package), lock-order cycle check over the static graph
    merged with the live witness graph, baseline applied. Banks a
    summary record next to the jaxpr analyzer's
    (``profiler.analysis_report()``, tag ``"concurrency"``)."""
    findings, edges = analyze_tree(root or default_root())
    observed = observed_edges() if include_witness else []
    findings.extend(check_lock_order(edges, observed))
    baseline = load_baseline(baseline_path) if baseline_path else {}
    active, blessed = apply_baseline(findings, baseline)
    report = ConcReport(active, blessed, edges, observed)
    trace_record("analysis", "concurrency",
                 findings=len(active), blessed=len(blessed),
                 rules=sorted({f.rule for f in active}),
                 static_edges=len(edges),
                 witnessed_edges=len(observed))
    return report


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m tony_tpu.analysis.concurrency",
        description="lock-discipline / lock-order / thread-hygiene "
                    "lint (make lint; tony analyze --concurrency)")
    p.add_argument("paths", nargs="*", help="package dirs or files "
                   "(default: the installed tony_tpu)")
    p.add_argument("--baseline", help="committed blessings file "
                   "(tests/signatures/concurrency.json)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from the current active "
                        "findings instead of failing on them")
    args = p.parse_args(list(argv) if argv is not None else None)
    roots = [Path(a) for a in args.paths] or [default_root()]
    findings: List[ConcFinding] = []
    edges: List[Tuple[str, str, str]] = []
    for r in roots:
        if not r.exists():
            # A typo'd path must fail the gate, not lint nothing.
            print(f"concurrency: path does not exist: {r}")
            return 2
        fs, es = analyze_tree(r)
        findings.extend(fs)
        edges.extend(es)
    findings.extend(check_lock_order(edges))
    baseline = load_baseline(args.baseline) if args.baseline else {}
    active, blessed = apply_baseline(findings, baseline)
    if args.update_baseline:
        if not args.baseline:
            print("--update-baseline needs --baseline <file>")
            return 2
        # Pre-apply findings + the existing baseline: still-firing
        # blessings keep their audited reasons, only the NEW active
        # findings pick up the placeholder (and stale entries prune).
        write_baseline(args.baseline, findings, existing=baseline)
        kept = sum(1 for f in blessed if f.fingerprint() in baseline)
        print(f"concurrency: baselined {len(active)} new finding(s), "
              f"kept {kept} existing blessing(s), into {args.baseline}")
        return 0
    for f in active:
        print(f)
    if active:
        print(f"concurrency: {len(active)} finding(s)")
        return 1
    print(f"concurrency: clean ({len(blessed)} blessed, "
          f"{len(edges)} static lock-order edge(s))")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
