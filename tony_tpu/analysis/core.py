"""The analyzer engine: report type and the two analyze entries.

Loaded lazily through the :mod:`tony_tpu.analysis` facade (PEP 562) so
jax-free consumers — the AST source lint, the CLI bootstrap that must set
XLA env vars BEFORE jax initializes — can import the package without
paying (or breaking on) a jax import. See the package docstring for the
rule-suite overview.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

from tony_tpu._trace import trace_record
from tony_tpu.analysis import jaxprwalk, rules, signature
from tony_tpu.analysis.jaxprwalk import (CollectiveEqn, collect_collectives,
                                         live_high_water)
from tony_tpu.analysis.rules import (SCALAR_NBYTES, Expected, Finding,
                                     Waiver, apply_waivers,
                                     expected_accum_collectives)
from tony_tpu.analysis.signature import (check_signature, diff_signature,
                                         step_signature)

__all__ = [
    "AnalysisReport", "CollectiveEqn", "Expected", "Finding", "Waiver",
    "analyze_accum_step", "analyze_jaxpr", "analyze_serve_step",
    "apply_waivers", "check_signature", "collect_collectives",
    "diff_signature", "expected_accum_collectives", "live_high_water",
    "step_signature",
]

# Trace-time side channel into the profiler registry (shared shim
# contract: lazy import, swallow-all, log-once — see tony_tpu._trace).
_record = functools.partial(trace_record, "analysis")


@dataclass(frozen=True)
class AnalysisReport:
    """One analyzed step: active findings (the gate fails on any), waived
    findings (accepted, with reasons), the full collective census, the
    signature digest, and the config metadata the run saw."""

    tag: str
    findings: Tuple[Finding, ...]
    waived: Tuple[Finding, ...]
    collectives: Tuple[CollectiveEqn, ...]
    signature: Dict[str, Any]
    config: Dict[str, Any]

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> Dict[str, Any]:
        return {
            "tag": self.tag, "ok": self.ok,
            "findings": [f.to_dict() for f in self.findings],
            "waived": [f.to_dict() for f in self.waived],
            "collectives": [
                {"kind": c.kind, "axes": list(c.axes), "nbytes": c.nbytes,
                 "path": c.path, "index": c.index, "src": c.src}
                for c in self.collectives],
            "signature": dict(self.signature),
            "config": dict(self.config),
        }

    def summary(self) -> str:
        lines = [f"[{self.tag}] {'CLEAN' if self.ok else 'FINDINGS'}: "
                 f"{len(self.findings)} finding(s), {len(self.waived)} "
                 f"waived, {len(self.collectives)} collective eqn(s), "
                 f"{self.signature.get('eqns', 0)} eqns, live high-water "
                 f"~{self.signature.get('live_high_water_nbytes', 0)} B"]
        for f in self.findings:
            lines.append(f"  {f.severity.upper()} {f.rule}/{f.kind}: "
                         f"{f.message}"
                         + (f"\n    at {f.provenance}" if f.provenance
                            else ""))
        for f in self.waived:
            lines.append(f"  waived {f.rule}/{f.kind} ({f.waived_by}): "
                         f"{f.message}")
        return "\n".join(lines)


def _bank(report: AnalysisReport) -> None:
    by_rule: Dict[str, int] = {}
    for f in report.findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    _record(report.tag, ok=report.ok, findings=len(report.findings),
            findings_by_rule=by_rule, waived=len(report.waived),
            collectives=dict(report.signature.get("collectives", {})),
            eqns=report.signature.get("eqns", 0),
            live_high_water_nbytes=report.signature.get(
                "live_high_water_nbytes", 0),
            config=dict(report.config))


def _jaxpr_findings(closed: Any, *, expected: Sequence[Expected],
                    gplan: Optional[Any], gather: str,
                    state: Optional[Any],
                    scalar_nbytes: int = SCALAR_NBYTES
                    ) -> Tuple[List[CollectiveEqn], List[Finding]]:
    """The jaxpr-side rules (1–3), shared by both analyze entries so a
    new rule can never land in one and silently miss the other."""
    colls = collect_collectives(closed)
    findings: List[Finding] = []
    findings += rules.reconcile_collectives(colls, expected,
                                            scalar_nbytes=scalar_nbytes)
    findings += rules.check_prefetch_chain(closed, gplan, gather)
    findings += rules.dtype_findings(closed)
    if state is not None:
        findings += rules.opt_state_findings(state)
    return colls, findings


def analyze_jaxpr(closed: Any, *, expected: Sequence[Expected] = (),
                  gplan: Optional[Any] = None, gather: str = "bucketed",
                  state: Optional[Any] = None,
                  donated: Optional[Sequence[bool]] = None,
                  waivers: Sequence[Waiver] = (), tag: str = "jaxpr",
                  scalar_nbytes: int = SCALAR_NBYTES,
                  config: Optional[Dict[str, Any]] = None
                  ) -> AnalysisReport:
    """Run the jaxpr-side rules (1–3 + signature) over one closed jaxpr —
    the seeded-violation test surface and the building block of
    :func:`analyze_accum_step` (which adds donation, rule 4, from the
    traced function's metadata)."""
    colls, findings = _jaxpr_findings(
        closed, expected=expected, gplan=gplan, gather=gather,
        state=state, scalar_nbytes=scalar_nbytes)
    active, waived = apply_waivers(findings, waivers)
    report = AnalysisReport(
        tag=tag, findings=tuple(active), waived=tuple(waived),
        collectives=tuple(colls),
        signature=step_signature(closed, donated, collectives=colls),
        config=dict(config or {}))
    _bank(report)
    return report


def analyze_serve_step(engine: Any, *, waivers: Sequence[Waiver] = (),
                       tag: str = "serve",
                       signature_path: Optional[str] = None,
                       batch: Optional[int] = None,
                       step: str = "decode") -> AnalysisReport:
    """Analyze a :class:`tony_tpu.serve.ServeEngine` decode step — the
    serving plane's day-one planner registration made auditable.

    Uses the engine's ``decode_traced`` hook (the same jit the loop
    runs) and reconciles the traced program against the engine's
    planner-registered expected collective set — which is EMPTY: a
    replica's decode must issue zero inter-chip collectives (its mesh
    shards memory, never cross-replica math), so any GSPMD-inserted
    reshard/gather surfaces as a rule-2 finding, not a latency mystery.
    Dtype policy (rule 3) and donation (rule 4 — the KV pools must be
    donated or every step doubles the cache's residency) run as on the
    accum steps; ``signature_path`` pins the digest (rule 5).

    ``step="verify"`` audits a :class:`tony_tpu.serve.SpecEngine`'s
    one-launch k-token verification through its ``verify_traced`` hook
    instead — the same rule suite over the speculative lane's hot path
    (zero collectives on a replica mesh, KV-pool donation, pinned
    signature), with the spec geometry in the report config.

    ``step="prefill"`` audits the chunked-prefill launch through
    ``prefill_traced`` — the ``(1, prefill_chunk)`` shape every
    non-final chunk rides. The ``route`` config pins it: chunked
    prefill must introduce no compiled step shape beyond the declared
    chunk geometry, and that program must satisfy the identical
    replica-step invariants (zero inter-chip collectives, donated KV
    pools)."""
    if step == "verify":
        jitted, args = engine.verify_traced(batch)
    elif step == "prefill":
        jitted, args = engine.prefill_traced()
    elif step == "decode":
        jitted, args = engine.decode_traced(batch)
    else:
        raise ValueError(f"unknown serve step {step!r} "
                         f"(decode|verify|prefill)")
    traced = jitted.trace(*args)
    closed = traced.jaxpr
    donate_argnums = tuple(getattr(traced, "donate_argnums", ()) or ())
    donated = _donated_flags(args, donate_argnums)
    if len(donated) != len(closed.jaxpr.invars):
        donated = None                    # static args shifted the map
    colls, findings = _jaxpr_findings(
        closed, expected=engine.expected_collectives(), gplan=None,
        gather="bucketed", state=None)
    # Donation (rule 4), flat-aware: traced.donate_argnums indexes FLAT
    # invars here (params flattens ahead of the pools), so resolve each
    # pool argument's flat span and require every position donated.
    arg_names = ("params", "pool_k", "pool_v", "tokens", "positions",
                 "tables", "flat_idx")
    spans = []
    pos = 0
    for a in args:
        n = len(jax.tree_util.tree_leaves(a))
        spans.append((pos, pos + n))
        pos += n
    donated_set = set(donate_argnums)
    for argnum in (1, 2):
        lo, hi = spans[argnum]
        if not all(i in donated_set for i in range(lo, hi)):
            nbytes = sum(jaxprwalk.aval_nbytes(l) for l in
                         jax.tree_util.tree_leaves(args[argnum]))
            findings.append(Finding(
                rule="donation", kind="undonated_argument",
                severity="error",
                message=(f"argument {argnum} ({arg_names[argnum]!r}, "
                         f"{nbytes} B) is not donated — every decode "
                         f"step would double the KV pool's residency"),
                provenance=f"donate_argnums={donate_argnums}"))
    sig = step_signature(closed, donated, collectives=colls)
    if signature_path is not None:
        for line in check_signature(sig, signature_path):
            findings.append(Finding(
                rule="signature", kind="signature_drift",
                severity="error",
                message=f"step signature drifted from the committed pin: "
                        f"{line}",
                provenance=str(signature_path)))
    active, waived = apply_waivers(findings, waivers)
    config = {
        "plane": f"serve_{step}", "ctx_pad": engine.ctx_pad,
        "block_size": engine.block_size, "q_block": engine.q_block,
        "n_blocks": engine.cache.n_blocks,
        "decode_buckets": list(engine.decode_buckets),
        "donate_argnums": list(donate_argnums),
    }
    if step == "verify":
        config["spec_k"] = int(engine.spec_k)
        config["draft"] = getattr(engine.draft, "kind", "?")
    if step == "prefill":
        config["prefill_chunk"] = engine.prefill_chunk
        config["prefix_cache"] = bool(engine.prefix_cache)
    report = AnalysisReport(
        tag=tag, findings=tuple(active), waived=tuple(waived),
        collectives=tuple(colls), signature=sig, config=config)
    _bank(report)
    return report


def _donated_flags(args: Sequence[Any],
                   donate_argnums: Sequence[int]) -> List[bool]:
    flags: List[bool] = []
    for i, a in enumerate(args):
        n = len(jax.tree_util.tree_leaves(a))
        flags.extend([i in donate_argnums] * n)
    return flags


def analyze_accum_step(stepper: Any, state: Any, batch: Any, *,
                       waivers: Sequence[Waiver] = (), tag: str = "accum",
                       expect_donated: Sequence[int] = (0,),
                       signature_path: Optional[str] = None
                       ) -> AnalysisReport:
    """THE top-level entry: analyze a ``make_accum_train_step`` stepper
    against the plans it will execute for ``state``'s layout.

    Uses the stepper's ``inspect(state)`` hook to recover the jitted
    step, the :class:`~tony_tpu.parallel.overlap.GradBuckets` /
    :class:`~tony_tpu.parallel.sched.GatherPlan` pair, and the config
    knobs; traces (never executes) the step; runs all five rules; banks
    the result into ``profiler.analysis_report()``. ``signature_path``
    additionally pins the digest against a committed snapshot
    (rule 5 — drift becomes a finding)."""
    info = stepper.inspect(state)
    traced = info["jitted"].trace(state, batch)
    closed = traced.jaxpr
    expected = expected_accum_collectives(
        info["plan"], info["gplan"], info["mesh"], gather=info["gather"],
        reduce_op=info["reduce_op"], hierarchy=info["hierarchy"],
        update=info["update"], fused=info.get("fused"),
        quant=bool(info.get("quant")))
    donate_argnums = tuple(getattr(traced, "donate_argnums", ()) or ())
    donated = _donated_flags((state, batch), donate_argnums)
    if len(donated) != len(closed.jaxpr.invars):
        donated = None                    # static args shifted the map
    colls, findings = _jaxpr_findings(
        closed, expected=expected, gplan=info["gplan"],
        gather=info["gather"], state=state)
    findings += rules.donation_findings(traced, (state, batch),
                                        ("state", "batch"),
                                        expect_donated=expect_donated)
    sig = step_signature(closed, donated, collectives=colls)
    if signature_path is not None:
        for line in check_signature(sig, signature_path):
            findings.append(Finding(
                rule="signature", kind="signature_drift",
                severity="error",
                message=f"step signature drifted from the committed pin: "
                        f"{line}",
                provenance=str(signature_path)))
    active, waived = apply_waivers(findings, waivers)
    config = {k: info[k] for k in ("update", "gather", "reduce_op",
                                   "hierarchy", "microbatches",
                                   "bucket_bytes", "donate", "quant")
              if k in info}
    config["donate_argnums"] = list(donate_argnums)
    report = AnalysisReport(
        tag=tag, findings=tuple(active), waived=tuple(waived),
        collectives=tuple(colls), signature=sig, config=config)
    _bank(report)
    return report
