"""Jaxpr-level sharding/collective invariant analyzer.

Every guarantee the training stack makes — "no replicated grads ever
materialize" (ZeRO-3), "forward gathers stay inside the prefetch window"
(the collective scheduler), "moment slots are f32 and pad rows inert"
(the fused optimizer) — is enforced by construction and spot-checked by
numerics tests. Nothing inspected the traced program to prove the
invariants still hold after the next refactor. TF-Replicator
(arXiv:1902.00465) argues the framework must own such cross-cutting
correctness properties rather than leave them to each user; this package
closes that loop: a static pass over the step's closed jaxpr,
cross-checked against the SAME planner artifacts the step executes.

Rule suite (see :mod:`tony_tpu.analysis.rules`):

1. **replication-leak** — any ``all_gather`` that materializes a full
   fsdp-sharded buffer outside the planned prefetch live window, plus the
   structural check that the ``optimization_barrier`` prefetch chain is
   intact;
2. **collective audit** — every ``psum``/``psum_scatter``/``all_gather``/
   ``all_to_all``/``ppermute`` equation reconciled against the planner's
   set (unplanned reshards AND planned-but-missing transfers, with
   equation provenance);
3. **dtype policy** — no silent f64, no bf16-carried reductions, f32
   moment slots;
4. **donation** — the state argument (params, opt slots) must be donated,
   or the finding names the argument and its byte cost;
5. **step signature** — a stable program digest pinned as a committed
   JSON snapshot (:mod:`tony_tpu.analysis.signature`).

Findings come back structured with a waiver mechanism
(:class:`Waiver`); each run banks a summary into
``tony_tpu.profiler.analysis_report()`` alongside the existing report
family. ``tony analyze`` (:mod:`tony_tpu.analysis.cli`) runs the suite
over the shipped train-step configs; ``make lint`` runs the companion
source lint (:mod:`tony_tpu.analysis.srclint`).

The facade is LAZY (PEP 562): importing ``tony_tpu.analysis`` touches no
jax. That keeps the jax-free consumers honest — the AST source lint, and
the ``tony analyze`` bootstrap that must set ``XLA_FLAGS`` BEFORE
anything initializes jax — while ``analysis.analyze_accum_step`` etc.
resolve to the jax-backed engine in :mod:`tony_tpu.analysis.core` on
first use.
"""

from __future__ import annotations

import importlib
from typing import Any

__all__ = [
    "AnalysisReport", "CollectiveEqn", "ConcFinding", "ConcReport",
    "Expected", "Finding",
    "SCALAR_NBYTES", "Waiver", "WitnessLock", "analyze_accum_step",
    "analyze_concurrency", "analyze_jaxpr",
    "analyze_serve_step",
    "apply_waivers", "check_lock_order", "check_signature",
    "collect_collectives",
    "diff_signature", "expected_accum_collectives", "live_high_water",
    "step_signature",
]

# name -> owning submodule (None = the name IS a submodule).
_LAZY = {
    "AnalysisReport": "core", "analyze_accum_step": "core",
    "analyze_jaxpr": "core", "analyze_serve_step": "core",
    "CollectiveEqn": "jaxprwalk", "collect_collectives": "jaxprwalk",
    "live_high_water": "jaxprwalk",
    "Expected": "rules", "Finding": "rules", "SCALAR_NBYTES": "rules",
    "Waiver": "rules", "apply_waivers": "rules",
    "expected_accum_collectives": "rules",
    "check_signature": "signature", "diff_signature": "signature",
    "step_signature": "signature",
    # The concurrency plane is jax-free like srclint — the facade keeps
    # it importable from `make lint` / gateway hosts without jax.
    "ConcFinding": "concurrency", "ConcReport": "concurrency",
    "WitnessLock": "concurrency", "analyze_concurrency": "concurrency",
    "check_lock_order": "concurrency",
    "cli": None, "concurrency": None, "core": None, "jaxprwalk": None,
    "rules": None,
    "signature": None, "srclint": None,
}


def __getattr__(name: str) -> Any:
    owner = _LAZY.get(name, "<missing>")
    if owner == "<missing>":
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    if owner is None:
        return importlib.import_module(f"{__name__}.{name}")
    return getattr(importlib.import_module(f"{__name__}.{owner}"), name)


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
