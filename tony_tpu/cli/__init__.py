"""``tony`` command-line interface (layer L6).

Mirrors ``tony-cli``'s ``ClusterSubmitter`` (upstream ``tony-cli/src/main/
java/com/linkedin/tony/cli/ClusterSubmitter.java``, unverified — SURVEY.md
§0/§2.2) plus the client flag surface of ``TonyClient#init``. The flags keep
the reference's names so existing TonY job definitions translate directly::

    tony submit --src_dir src/ --executes train.py --conf_file tony.xml \
                --conf tony.worker.instances=2 --framework jax

Subcommands:

* ``submit``  — submit a job and monitor it to completion (exit code = job's)
* ``history`` — list finished/running jobs, or show one job's events
* ``notebook``— single-container notebook session behind the TCP proxy
  (reference: ``NotebookSubmitter``)
* ``version`` — print the framework version
"""

from __future__ import annotations

import argparse
from typing import List, Optional

from tony_tpu import __version__
from tony_tpu import conf as conf_mod
from tony_tpu.conf import TonyConfig


def _parse_conf_overrides(pairs: List[str]) -> dict:
    out = {}
    for pair in pairs:
        if "=" not in pair:
            raise SystemExit(f"--conf expects key=value, got {pair!r}")
        k, _, v = pair.partition("=")
        out[k.strip()] = v.strip()
    return out


def build_conf(args: argparse.Namespace) -> TonyConfig:
    """Effective config from file + CLI switches + ``--conf`` overrides —
    the reference's layering (SURVEY.md §5.6), highest precedence last."""
    cfg = TonyConfig()
    if args.conf_file:
        cfg.merge_file(args.conf_file)
    if getattr(args, "executes", None):
        cfg.set("tony.application.executes", args.executes)
    if getattr(args, "framework", None):
        cfg.set(conf_mod.APPLICATION_FRAMEWORK, args.framework)
    if getattr(args, "name", None):
        cfg.set(conf_mod.APPLICATION_NAME, args.name)
    if getattr(args, "python_venv", None):
        cfg.set(conf_mod.PYTHON_VENV, args.python_venv)
    if getattr(args, "python_binary_path", None):
        cfg.set(conf_mod.PYTHON_BINARY, args.python_binary_path)
    cfg.merge_overrides(_parse_conf_overrides(args.conf or []))
    return cfg


def cmd_submit(args: argparse.Namespace) -> int:
    from tony_tpu.client import TonyClient
    cfg = build_conf(args)
    client = TonyClient(cfg, src_dir=args.src_dir, workdir=args.workdir,
                        am_host=args.am_host, quiet=args.quiet)
    return client.run(timeout=args.timeout)


def cmd_serve(args: argparse.Namespace) -> int:
    """Submit an online-serving job (tony_tpu.serve): N replica
    containers, each restoring the training checkpoint onto its own
    mesh (bf16 dtype policy by default) and running the continuous-
    batching engine behind the control-plane RPC wire. ``--max_replicas``
    above ``--replicas`` arms the AM's heartbeat-driven autoscaler."""
    import json as json_mod
    from pathlib import Path

    from tony_tpu.client import TonyClient

    cfg = TonyConfig()
    if args.conf_file:
        cfg.merge_file(args.conf_file)
    # Replicas are independent jax worlds — no rendezvous gang — so the
    # framework is "standalone"; and a serving fleet should outlive one
    # crashed replica, so fail-fast is off (the autoscaler repairs the
    # floor instead).
    cfg.set(conf_mod.APPLICATION_FRAMEWORK, "standalone")
    cfg.set(conf_mod.APPLICATION_NAME,
            args.name or f"tony-serve-{args.model}")
    cfg.set(conf_mod.APPLICATION_STOP_ON_FAILURE, "false")
    # Disaggregated split (--role prefill=2,decode=4): each role becomes
    # its OWN jobtype — the heterogeneous-gang wiring — sharing the
    # serve.* engine config; the per-jobtype role key tells each replica
    # which half of the handoff protocol it fronts. Validate the spec at
    # SUBMIT: a typo'd role that silently became a colocated gang would
    # serve the wrong topology without a word.
    if args.role:
        roles = {}
        for part in args.role.split(","):
            name, _, count = part.partition("=")
            name = name.strip()
            if name not in ("prefill", "decode", "colocated"):
                raise SystemExit(f"--role: unknown role {name!r} "
                                 f"(prefill|decode|colocated)")
            if name in roles:
                raise SystemExit(f"--role: duplicate role {name!r}")
            try:
                n = int(count)
            except ValueError:
                raise SystemExit(f"--role: need {name}=<count>, got "
                                 f"{part!r}") from None
            if n < 1:
                raise SystemExit(f"--role: {name} needs >= 1 replica, "
                                 f"got {n}")
            roles[name] = n
        if ("prefill" in roles) != ("decode" in roles):
            raise SystemExit("--role: a split fleet needs BOTH a "
                             "prefill and a decode gang (the router "
                             "falls back to colocated only per-request, "
                             "not per-topology)")
        for name, n in roles.items():
            cfg.set(conf_mod.instances_key(name), str(n))
            cfg.set(conf_mod.command_key(name),
                    "python -m tony_tpu.serve.replica")
            cfg.set(conf_mod.serve_role_key(name), name)
    else:
        cfg.set(conf_mod.instances_key("serve"), str(args.replicas))
        cfg.set(conf_mod.command_key("serve"),
                "python -m tony_tpu.serve.replica")
    cfg.set(conf_mod.SERVE_MODEL, args.model)
    if args.model_kwargs:
        json_mod.loads(args.model_kwargs)   # validate at submit, not launch
        cfg.set(conf_mod.SERVE_MODEL_KWARGS, args.model_kwargs)
    # Continuous publication follow mode (tony_tpu.publish): --follow
    # names a TRAIN job's dir (its serialized conf supplies the ckpt
    # dir) or a bare ckpt dir, and arms tony.publish.follow — the AM
    # polls the published pointer and rolls the fleet onto every new
    # version the train gang commits.
    ckpt_dir = args.ckpt_dir
    if getattr(args, "follow", None):
        from tony_tpu import constants

        followed = Path(args.follow).resolve()
        conf_path = followed / constants.TONY_JOB_JSON
        if conf_path.is_file():
            followed_ckpt = TonyConfig.load(conf_path).get(
                conf_mod.CKPT_DIR)
            if not followed_ckpt:
                raise SystemExit(
                    f"--follow: job at {followed} has no "
                    f"{conf_mod.CKPT_DIR} in its conf — nothing to "
                    f"follow")
            ckpt_dir = followed_ckpt
        else:
            ckpt_dir = str(followed)   # bare ckpt dir
        cfg.set(conf_mod.PUBLISH_FOLLOW, "true")
    if not ckpt_dir:
        raise SystemExit("need --ckpt_dir (or --follow <jobdir>)")
    # Absolute: replicas run with a different cwd.
    cfg.set(conf_mod.SERVE_CKPT_DIR, str(Path(ckpt_dir).resolve()))
    cfg.set(conf_mod.SERVE_DTYPE_POLICY, args.dtype_policy)
    cfg.set(conf_mod.SERVE_CTX_MAX, str(args.ctx_max))
    if args.mesh:
        json_mod.loads(args.mesh)
        cfg.set(conf_mod.SERVE_MESH, args.mesh)
    if args.max_replicas is not None:
        cfg.set(conf_mod.SERVE_REPLICAS_MAX, str(args.max_replicas))
    # Speculative decoding lane: --spec_k arms draft-and-verify; a named
    # --draft_model restores a second (smaller) ckpt next to the target,
    # otherwise the self-drafting n-gram fallback runs. Validate the
    # flag COMBINATIONS at submit, not replica launch: a draft flag that
    # silently dropped would serve the wrong lane without a word.
    if args.spec_k and not 1 <= args.spec_k <= 15:
        # The replica's row block is q_block=16 and the k+1 verify rows
        # must fit it (SpecEngine enforces the same bound at launch).
        raise SystemExit(f"--spec_k must be in [1, 15] (k+1 verify rows "
                         f"ride the 16-row block), got {args.spec_k}")
    for flag, val in (("--draft_model_kwargs", args.draft_model_kwargs),
                      ("--draft_ckpt_dir", args.draft_ckpt_dir)):
        if val and not args.draft_model:
            raise SystemExit(f"{flag} needs --draft_model (without one "
                             f"the replica runs the n-gram self-draft "
                             f"and the flag would be silently ignored)")
    # Prefix caching / chunked prefill (tony_tpu.serve PR 13): validate
    # the chunk geometry at submit — the engine would reject a
    # non-row-block multiple at launch, replica by replica.
    if args.prefill_chunk and (args.prefill_chunk <= 0
                               or args.prefill_chunk % 16):
        raise SystemExit(f"--prefill_chunk must be a positive multiple "
                         f"of the 16-row block, got {args.prefill_chunk}")
    # KV memory hierarchy (tony_tpu.serve PR 16): host tier size and the
    # persistent prefix store. Validate at submit — a negative tier or a
    # relative store path (replicas run with a different cwd) would fail
    # replica by replica at launch.
    if args.host_blocks < 0:
        raise SystemExit(f"--host_blocks must be >= 0, got "
                         f"{args.host_blocks}")
    if args.host_blocks:
        cfg.set(conf_mod.SERVE_HOST_BLOCKS, str(args.host_blocks))
    if args.prefix_store:
        cfg.set(conf_mod.SERVE_PREFIX_STORE,
                str(Path(args.prefix_store).resolve()))
    # Replica cold-start plane (tony_tpu.ckpt.aot PR 17): persisted AOT
    # executables + warm-standby pool + the demotion daemon watermark.
    # Same submit-time validation story: the engine rejects a bad
    # watermark at launch, replica by replica; the cache dir must be
    # absolute for the same cwd reason as the prefix store.
    if args.aot_cache:
        cfg.set(conf_mod.SERVE_AOT_CACHE,
                str(Path(args.aot_cache).resolve()))
    if args.warm_standby < 0:
        raise SystemExit(f"--warm_standby must be >= 0, got "
                         f"{args.warm_standby}")
    if args.warm_standby:
        cfg.set(conf_mod.SERVE_WARM_STANDBY, str(args.warm_standby))
    if not 0.0 <= args.demote_watermark <= 1.0:
        raise SystemExit(f"--demote_watermark must be a pool fraction "
                         f"in [0, 1], got {args.demote_watermark}")
    if args.demote_watermark and not args.host_blocks:
        raise SystemExit("--demote_watermark needs --host_blocks > 0 "
                         "(the daemon demotes into the host tier; "
                         "without one the flag would be silently "
                         "ignored)")
    if args.demote_watermark:
        cfg.set(conf_mod.SERVE_DEMOTE_WATERMARK,
                str(args.demote_watermark))
    # QoS / history plane (tony_tpu.serve.qos PR 18): validate the
    # tenant spec at submit — parse_tenants raises on empty names,
    # duplicates, and non-positive weights, which the replica would
    # otherwise reject at launch, replica by replica.
    if args.tenants:
        from tony_tpu.serve.qos import parse_tenants

        try:
            parse_tenants(args.tenants)
        except ValueError as e:
            raise SystemExit(f"--tenants: {e}")
        cfg.set(conf_mod.SERVE_QOS_TENANTS, args.tenants)
    if args.qos_max_queue < 0:
        raise SystemExit(f"--qos_max_queue must be >= 0, got "
                         f"{args.qos_max_queue}")
    if args.qos_max_queue:
        if not args.tenants:
            raise SystemExit("--qos_max_queue needs --tenants (the cap "
                             "is per tenant class; without a spec it "
                             "would be silently ignored)")
        cfg.set(conf_mod.SERVE_QOS_MAX_QUEUE, str(args.qos_max_queue))
    if args.slo_target_ms:
        # Two grammars, one flag: a bare number is the fleet-wide target
        # (the PR 18 lane, byte-identical behavior), while a tenant CSV
        # (gold:200,silver:800) sets PER-TENANT targets — the autoscaler
        # then scales on the worst tenant's p99-vs-target. Same strict
        # parser as --tenants: a typo'd spec must die at submit, not
        # silently autoscale on the wrong signal.
        try:
            target = float(args.slo_target_ms)
        except ValueError:
            from tony_tpu.serve.qos import parse_tenants

            try:
                targets = parse_tenants(args.slo_target_ms)
            except ValueError as e:
                raise SystemExit(f"--slo_target_ms: {e}")
            if any(v <= 0 for v in targets.values()):
                raise SystemExit("--slo_target_ms: per-tenant targets "
                                 "must be > 0 ms")
            cfg.set(conf_mod.SERVE_SLO_TARGETS, args.slo_target_ms)
        else:
            if target < 0:
                raise SystemExit(f"--slo_target_ms must be >= 0, got "
                                 f"{target}")
            if target:
                cfg.set(conf_mod.SERVE_SLO_TARGET_MS, str(target))
    if args.prefix_cache:
        cfg.set(conf_mod.SERVE_PREFIX_CACHE, "true")
    if args.prefill_chunk:
        cfg.set(conf_mod.SERVE_PREFILL_CHUNK, str(args.prefill_chunk))
    if args.spec_k:
        cfg.set(conf_mod.SERVE_SPEC_K, str(args.spec_k))
    if args.draft_model:
        if not args.spec_k:
            raise SystemExit("--draft_model needs --spec_k > 0 (the "
                             "draft depth arms the speculative lane)")
        cfg.set(conf_mod.SERVE_DRAFT_MODEL, args.draft_model)
        if args.draft_model_kwargs:
            json_mod.loads(args.draft_model_kwargs)  # validate at submit
            cfg.set(conf_mod.SERVE_DRAFT_MODEL_KWARGS,
                    args.draft_model_kwargs)
        if args.draft_ckpt_dir:
            cfg.set(conf_mod.SERVE_DRAFT_CKPT_DIR,
                    str(Path(args.draft_ckpt_dir).resolve()))
    cfg.merge_overrides(_parse_conf_overrides(args.conf or []))
    client = TonyClient(cfg, workdir=args.workdir, am_host=args.am_host,
                        quiet=args.quiet)
    return client.run(timeout=args.timeout)


def cmd_route(args: argparse.Namespace) -> int:
    """Run the fleet's request router (tony_tpu.serve.router): a
    gateway-side RPC front that polls the AM's ``serve_endpoints`` verb
    for the live replica set and dispatches ``generate`` calls by
    prefix-cache overlap, queue depth, and p99 — with sticky session
    affinity and failover re-dispatch. Jax-free: runs on any gateway
    host."""
    import threading

    from tony_tpu.serve.router import (RequestRouter, RouterPolicy,
                                       RouterServer)

    policy = RouterPolicy(cache_weight=args.cache_weight,
                          queue_weight=args.queue_weight,
                          p99_weight=args.p99_weight)
    router = RequestRouter(block_size=args.block_size, policy=policy)
    server = RouterServer(router, port=args.port, am_address=args.am,
                          poll_s=args.poll_s)
    server.start()
    print(f"[tony-route] listening on {server.address}, tracking "
          f"replicas via AM {args.am}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


def cmd_publish(args: argparse.Namespace) -> int:
    """Publish a committed checkpoint step for serve fleets to hot-swap
    onto (tony_tpu.publish): stage-and-rename the versioned pointer
    file over the ckpt root. Jax-free — runs anywhere the ckpt dir is
    mounted; the train loop's ``publish_every`` knob does the same
    thing automatically on the save cadence."""
    from tony_tpu.publish import PublishError, latest_publication, \
        publish_step

    try:
        rec = publish_step(args.ckpt_dir, args.step,
                           note=args.note or "")
    except (PublishError, OSError) as e:
        print(f"tony publish: {e}")
        return 1
    print(f"published v{rec['version']} -> step {rec['step']} "
          f"({rec['manifest']})")
    prev = latest_publication(args.ckpt_dir)
    if prev is None or prev["version"] != rec["version"]:
        print("warning: pointer read-back disagrees — concurrent "
              "publisher?")
    return 0


def cmd_aot(args: argparse.Namespace) -> int:
    """AOT-cache maintenance. ``gc`` drops entries whose stored runtime
    fingerprint no live config can produce — a jax/backend upgrade
    strands every old entry (the get() path already refuses them);
    this reclaims the disk."""
    if args.action != "gc":
        return 2
    from tony_tpu.ckpt.aot import AOTCache

    cache = AOTCache(args.cache)
    dropped, kept, freed = cache.gc(dry_run=args.dry_run)
    verb = "would drop" if args.dry_run else "dropped"
    print(f"tony aot gc: {verb} {dropped} stale entr"
          f"{'y' if dropped == 1 else 'ies'} ({freed} bytes), "
          f"{kept} live kept under {args.cache}")
    return 0


def cmd_history(args: argparse.Namespace) -> int:
    from tony_tpu.history import main as history_main
    return history_main(args)


def cmd_notebook(args: argparse.Namespace) -> int:
    from tony_tpu.notebook import main as notebook_main
    return notebook_main(args)


def cmd_azkaban(args: argparse.Namespace) -> int:
    from tony_tpu.azkaban import main as azkaban_main
    return azkaban_main(args)


def cmd_profile(args: argparse.Namespace) -> int:
    """Capture a trace from every rank of a RUNNING job into its history
    dir (reference gap closed per SURVEY.md §5.1: hook + collection)."""
    from pathlib import Path

    from tony_tpu import constants
    from tony_tpu.profiler import collect_traces, endpoints_from_callback_info
    from tony_tpu.rpc import RpcClient, RpcError

    live = _live_am(args)
    if live is None:
        return 1
    job_dir, addr, token = live
    try:
        with RpcClient(addr, token=token, timeout=10.0) as c:
            info = c.call("get_task_callback_info")
    except (RpcError, OSError) as e:
        print(f"AM RPC failed: {e}")
        return 1
    endpoints = endpoints_from_callback_info(info)
    if not endpoints:
        print("no profiler endpoints registered — set "
              "tony.task.profiler.enabled=true on the job")
        return 1
    # The AM's history root (may be overridden by tony.history.location).
    conf_path = job_dir / constants.TONY_JOB_JSON
    history = job_dir / "history"
    if conf_path.is_file():
        loc = TonyConfig.load(conf_path).get(conf_mod.HISTORY_LOCATION)
        if loc:
            history = Path(loc)
    collected = collect_traces(endpoints, history, args.app_id,
                               duration_ms=args.duration_ms)
    return 0 if collected else 1


def _job_dir_of(args: argparse.Namespace):
    from pathlib import Path

    from tony_tpu.util import default_workdir

    workdir = Path(args.workdir) if args.workdir else default_workdir()
    # Resolved: the trace logdir travels inside the profiler RPC and the
    # SERVER (the profiled process, different cwd) may write the xplane
    # files itself — a relative path lands in the wrong tree.
    return (workdir / args.app_id).resolve()


def _live_am(args: argparse.Namespace):
    """(job_dir, am_address, token) of a RUNNING job, or None (reported)
    — the shared resolution for every verb that dials a live AM."""
    job_dir = _job_dir_of(args)
    addr_file = job_dir / "am.address"
    if not addr_file.is_file():
        print(f"no live AM address for {args.app_id} under "
              f"{job_dir.parent} (already finished, or wrong --workdir?)")
        return None
    token_file = job_dir / "am.token"
    try:
        token = token_file.read_text().strip() \
            if token_file.is_file() else None
        addr = addr_file.read_text().strip()
    except OSError as e:   # e.g. 0600 token owned by the submitter
        print(f"cannot read AM credentials under {job_dir}: {e}")
        return None
    return job_dir, addr, token


def cmd_kill(args: argparse.Namespace) -> int:
    """Kill a RUNNING job from outside its submitting client (reference
    analogue: ``yarn application -kill``)."""
    from tony_tpu.rpc import RpcClient, RpcError

    live = _live_am(args)
    if live is None:
        return 1
    _, addr, token = live
    try:
        with RpcClient(addr, token=token, timeout=10.0) as c:
            c.call("finish_application",
                   reason=f"killed via tony kill by {args.reason or 'cli'}")
    except (RpcError, OSError) as e:
        print(f"kill RPC failed: {e}")
        return 1
    print(f"kill requested for {args.app_id}")
    return 0


def cmd_resize(args: argparse.Namespace) -> int:
    """Operator-triggered elastic resize of a RUNNING job's training
    gang: the AM drains the gang (each survivor commits model + data
    cursor), re-gangs at the new worker count, and restores — the
    ``tony_tpu.am.resize`` state machine. Needs the job submitted with
    ``tony.resize.enabled=true``; a disabled job reports the refusal
    here instead of silently ignoring the verb."""
    from tony_tpu.rpc import RpcClient, RpcError

    if args.num_workers < 1:
        print(f"--num_workers must be >= 1, got {args.num_workers}")
        return 1
    live = _live_am(args)
    if live is None:
        return 1
    _, addr, token = live
    try:
        with RpcClient(addr, token=token, timeout=10.0) as c:
            c.call("resize", num_workers=args.num_workers)
    except (RpcError, OSError) as e:
        print(f"resize RPC failed: {e}")
        return 1
    print(f"resize to {args.num_workers} worker(s) requested for "
          f"{args.app_id} (drain -> commit -> re-gang -> restore; "
          f"follow with: tony history show {args.app_id})")
    return 0


def cmd_logs(args: argparse.Namespace) -> int:
    """Print per-container logs of a job on the local substrate
    (reference analogue: ``yarn logs -applicationId``). Remote (tpu-vm)
    containers keep their logs on the worker hosts."""
    from collections import deque

    from tony_tpu import constants

    job_dir = _job_dir_of(args)
    containers = sorted((job_dir / "containers").glob("*")) \
        if (job_dir / "containers").is_dir() else []
    if not containers:
        print(f"no container logs under {job_dir} "
              f"(wrong --workdir, or a remote-substrate job?)")
        return 1
    tail = max(0, args.tail)
    printed_any = False
    for cdir in containers:
        for name in (constants.EXECUTOR_LOG_NAME,
                     constants.USER_STDOUT_NAME, constants.USER_STDERR_NAME):
            f = cdir / name
            if not f.is_file() or f.stat().st_size == 0:
                continue
            printed_any = True
            # Bounded memory either way: deque for --tail, streamed
            # line-by-line otherwise — container logs can be GBs.
            with open(f, errors="replace") as fh:
                if tail:
                    shown = deque(fh, maxlen=tail)
                    print(f"===== {cdir.name}/{name} "
                          f"(last {len(shown)} lines) =====")
                    for line in shown:
                        print(line.rstrip("\n"))
                else:
                    print(f"===== {cdir.name}/{name} =====")
                    for line in fh:
                        print(line.rstrip("\n"))
    if not printed_any:
        # Scripts need 'no logs yet' distinguishable from 'logs shown'.
        print(f"no non-empty logs yet under {job_dir / 'containers'}")
        return 1
    return 0


def cmd_analyze(args: argparse.Namespace) -> int:
    """Run the jaxpr invariant analyzer (and optionally the source lint)
    over the shipped train-step configs — the static half of the tier-1
    gate, runnable anywhere the CPU wheel is (no TPU needed). The import
    is jax-free (the analysis facade is lazy), so ``analysis_cli.main``
    still gets to set the virtual-CPU-mesh env BEFORE jax initializes."""
    from tony_tpu.analysis import cli as analysis_cli

    return analysis_cli.main(args)


def cmd_version(_args: argparse.Namespace) -> int:
    print(f"tony-tpu {__version__}")
    return 0


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tony", description="TonY-TPU: TPU-native distributed-job orchestrator")
    sub = p.add_subparsers(dest="command", required=True)

    s = sub.add_parser("submit", help="submit a job and monitor to completion")
    s.add_argument("--src_dir", help="user source directory to stage")
    s.add_argument("--executes", help="command to run in each task container")
    s.add_argument("--conf_file", help="tony.xml / JSON job config")
    s.add_argument("--conf", action="append", metavar="KEY=VALUE",
                   help="config override (repeatable)")
    s.add_argument("--framework", help="jax|tensorflow|pytorch|horovod|mxnet|standalone")
    s.add_argument("--name", help="application name")
    s.add_argument("--python_venv", help="virtualenv archive/dir to ship")
    s.add_argument("--python_binary_path", help="python interpreter inside the venv")
    s.add_argument("--workdir", help="client work dir (default ~/.tony-tpu/jobs)")
    s.add_argument("--am_host", default="127.0.0.1",
                   help="address executors use to reach the AM")
    s.add_argument("--timeout", type=float, default=None,
                   help="client-side monitor timeout in seconds")
    s.add_argument("--quiet", action="store_true")
    s.set_defaults(fn=cmd_submit)

    sv = sub.add_parser("serve", help="serve a trained checkpoint: replica "
                        "containers with continuous batching and "
                        "heartbeat-driven autoscale")
    sv.add_argument("--model", required=True,
                    help="registered model name (e.g. llama2-7b)")
    sv.add_argument("--model_kwargs", help="JSON dict of model kwargs "
                    "(quant lanes, layer count overrides, ...)")
    sv.add_argument("--ckpt_dir", default=None,
                    help="training checkpoint directory to serve "
                         "(or use --follow)")
    sv.add_argument("--follow", default=None, metavar="JOBDIR|CKPT_DIR",
                    help="follow a train job's continuous publications: "
                         "a job dir (its conf supplies the ckpt dir) or "
                         "a bare ckpt dir — the AM polls the published "
                         "pointer and hot-swaps the fleet onto every "
                         "new version, one replica at a time")
    sv.add_argument("--replicas", type=int, default=1,
                    help="initial replica count (the autoscale floor)")
    sv.add_argument("--max_replicas", type=int, default=None,
                    help="autoscale ceiling (> --replicas arms the "
                         "AM's heartbeat-driven scaler); with --role "
                         "it is the FLEET ceiling, apportioned across "
                         "the gangs proportional to their floors "
                         "(per-gang override: "
                         "tony.serve.replicas.max.<jobtype>)")
    sv.add_argument("--dtype_policy", default="bf16", choices=("bf16", "f32"),
                    help="restore-time cast: f32 master -> serving dtype")
    sv.add_argument("--ctx_max", type=int, default=2048,
                    help="max positions per sequence (KV buffer extent)")
    sv.add_argument("--mesh", help="JSON MeshSpec kwargs for each "
                    "replica's own mesh (e.g. '{\"fsdp\": 2}')")
    sv.add_argument("--prefix_cache", action="store_true",
                    help="arm block-level KV prefix sharing: admissions "
                         "whose prompt chain-matches cached blocks skip "
                         "that prefill outright (bitwise transparent)")
    sv.add_argument("--prefill_chunk", type=int, default=0,
                    help="chunked prefill rows per iteration (a 16-row "
                         "block multiple; 0 = monolithic): long prompts "
                         "interleave with decode instead of stalling it")
    sv.add_argument("--role", default=None, metavar="ROLE=N[,ROLE=N...]",
                    help="disaggregated prefill/decode split: per-role "
                         "gang sizes, e.g. 'prefill=2,decode=4' — each "
                         "role becomes its OWN jobtype (heterogeneous "
                         "gangs in one job) and the router ships KV "
                         "blocks prefill->decode over the RPC wire; "
                         "omit for the classic colocated fleet")
    sv.add_argument("--host_blocks", type=int, default=0,
                    help="pinned host-RAM KV tier size in blocks (0 = "
                         "off): cold published stems demote to host "
                         "instead of dying at LRU eviction, and idle "
                         "conversations park between turns — resumed "
                         "turns skip their re-prefill bitwise")
    sv.add_argument("--prefix_store", default=None, metavar="DIR",
                    help="persistent prefix store directory: hot "
                         "published stems commit to disk through the "
                         "ckpt plane's atomic rename, and fresh or "
                         "scale-up replicas warm their prefix tier "
                         "from the store on start")
    sv.add_argument("--aot_cache", default=None, metavar="DIR",
                    help="persisted AOT compile cache directory: step "
                         "executables compiled once serialize next to "
                         "the ckpt manifest, and every later replica "
                         "of the same (topology, config, jax) family "
                         "deserializes in milliseconds instead of "
                         "re-tracing — the scale-up grant's cold-start "
                         "killer")
    sv.add_argument("--warm_standby", type=int, default=0,
                    help="warm-standby pool size per serve jobtype "
                         "(0 = off): compiled-and-idle replicas held "
                         "ahead of the traffic curve; the AM promotes "
                         "one on scale-up instead of a cold grant "
                         "(per-gang override: "
                         "tony.serve.warm-standby.<jobtype>)")
    sv.add_argument("--demote_watermark", type=float, default=0.0,
                    help="device-pool occupancy fraction above which "
                         "the engine loop pre-demotes cold KV blocks "
                         "into the --host_blocks tier (0 = off): "
                         "eviction pressure is drained ahead of the "
                         "work arriving, like the warm pool itself")
    sv.add_argument("--tenants", default=None, metavar="NAME:W[,NAME:W...]",
                    help="tenant classes with weighted-fair KV-block "
                         "budgets, e.g. gold:3,silver:1 (bare name = "
                         "weight 1); arms per-tenant admission QoS on "
                         "every replica — absent, serving is "
                         "byte-identical to an untagged fleet")
    sv.add_argument("--qos_max_queue", type=int, default=0,
                    help="per-tenant queue cap: past it a tenant's "
                         "submits get typed retryable back-pressure "
                         "(0 = unbounded; needs --tenants)")
    sv.add_argument("--slo_target_ms", default="",
                    metavar="MS|TENANT:MS[,TENANT:MS...]",
                    help="p99 latency target arming SLO-mode "
                         "autoscaling: the gang scales on p99-vs-target "
                         "from the heartbeat latency windows the "
                         "history plane logs (0/empty = queue-depth "
                         "mode); a tenant CSV like gold:200,silver:800 "
                         "sets PER-TENANT targets and the gang scales "
                         "on the worst tenant's p99 (needs the replicas "
                         "publishing per-tenant windows via --tenants)")
    sv.add_argument("--spec_k", type=int, default=0,
                    help="speculative decoding draft depth (0 = off; "
                         "k tokens drafted, verified in ONE target "
                         "forward — greedy outputs stay bitwise "
                         "identical)")
    sv.add_argument("--draft_model", help="registered draft model name "
                    "(omit for the self-drafting n-gram fallback)")
    sv.add_argument("--draft_model_kwargs",
                    help="JSON dict of draft model kwargs")
    sv.add_argument("--draft_ckpt_dir",
                    help="draft model checkpoint dir (default: the "
                         "target's --ckpt_dir)")
    sv.add_argument("--conf_file", help="tony.xml / JSON job config")
    sv.add_argument("--conf", action="append", metavar="KEY=VALUE")
    sv.add_argument("--name", help="application name")
    sv.add_argument("--workdir", help="client work dir")
    sv.add_argument("--am_host", default="127.0.0.1")
    sv.add_argument("--timeout", type=float, default=None)
    sv.add_argument("--quiet", action="store_true")
    sv.set_defaults(fn=cmd_serve)

    rt = sub.add_parser("route", help="run the fleet request router: "
                        "routes generate RPCs over the live replica set "
                        "by prefix-cache overlap and load")
    rt.add_argument("--am", required=True,
                    help="AM RPC address (host:port) to poll for the "
                         "live replica set")
    rt.add_argument("--port", type=int, default=0,
                    help="router RPC port (0 = any)")
    rt.add_argument("--block_size", type=int, default=16,
                    help="fleet KV block size (must match the replicas' "
                         "engine geometry — the chain keys are "
                         "block-aligned)")
    rt.add_argument("--cache_weight", type=float, default=4.0)
    rt.add_argument("--queue_weight", type=float, default=1.0)
    rt.add_argument("--p99_weight", type=float, default=0.5)
    rt.add_argument("--poll_s", type=float, default=2.0,
                    help="AM membership poll interval")
    rt.set_defaults(fn=cmd_route)

    h = sub.add_parser("history", help="list jobs or show one job's events")
    h.add_argument("action", choices=["list", "show", "serve", "bill"],
                   help="list all jobs / show one job / serve the web "
                        "portal / roll up a tenant's billed tokens")
    h.add_argument("app_id", nargs="?",
                   help="application id (for show) or tenant name (for "
                        "bill; omit to bill every tenant)")
    h.add_argument("--history", dest="history_dir",
                   help="history root dir (default: scan client workdir)")
    h.add_argument("--port", type=int, default=19885,
                   help="portal port (for serve)")
    h.add_argument("--bind", default="127.0.0.1",
                   help="portal bind address (default loopback; job configs "
                        "are exposed unauthenticated — widen deliberately)")
    h.add_argument("--json", action="store_true",
                   help="emit the billing rows as JSON (for bill)")
    h.add_argument("--csv", action="store_true",
                   help="emit the billing rows as CSV (for bill)")
    h.add_argument("--since", default=None, metavar="WHEN",
                   help="clip the billing window start: epoch seconds, "
                        "YYYY-MM-DD, or 'YYYY-MM-DD HH:MM:SS' (for bill)")
    h.add_argument("--until", default=None, metavar="WHEN",
                   help="clip the billing window end (same formats; "
                        "for bill)")
    h.set_defaults(fn=cmd_history)

    pb = sub.add_parser("publish", help="publish a committed checkpoint "
                        "step for serve fleets to hot-swap onto")
    pb.add_argument("ckpt_dir", help="checkpoint root (the train job's "
                    "tony.ckpt.dir)")
    pb.add_argument("--step", type=int, default=None,
                    help="committed step to publish (default: newest)")
    pb.add_argument("--note", default="",
                    help="free-form note recorded in the pointer")
    pb.set_defaults(fn=cmd_publish)

    ao = sub.add_parser("aot", help="AOT compile-cache maintenance")
    ao.add_argument("action", choices=["gc"],
                    help="gc: drop entries whose runtime fingerprint no "
                         "live config can produce")
    ao.add_argument("--cache", required=True, metavar="DIR",
                    help="AOT cache directory")
    ao.add_argument("--dry-run", dest="dry_run", action="store_true",
                    help="report what would be dropped, delete nothing")
    ao.set_defaults(fn=cmd_aot)

    n = sub.add_parser("notebook", help="run a notebook/command in one "
                       "container behind a TCP proxy")
    n.add_argument("--src_dir", help="source directory to stage")
    n.add_argument("--executes", required=True,
                   help="notebook/server command; it should bind $TB_PORT")
    n.add_argument("--conf_file", help="tony.xml / JSON job config")
    n.add_argument("--conf", action="append", metavar="KEY=VALUE")
    n.add_argument("--workdir", help="client work dir")
    n.add_argument("--port", type=int, default=0,
                   help="local proxy port (0 = ephemeral)")
    n.set_defaults(fn=cmd_notebook)

    a = sub.add_parser("azkaban", help="submit from an Azkaban-style "
                       ".job properties file")
    a.add_argument("job_file", help="java-properties job file "
                   "(tony.* keys pass through)")
    a.add_argument("--workdir", help="client work dir")
    a.add_argument("--timeout", type=float, default=None)
    a.set_defaults(fn=cmd_azkaban)

    pr = sub.add_parser("profile", help="capture a trace from every rank "
                        "of a running job into its history dir")
    pr.add_argument("app_id", help="application id of a RUNNING job")
    pr.add_argument("--workdir", help="client work dir (default ~/.tony-tpu/jobs)")
    pr.add_argument("--duration_ms", type=int, default=2000,
                    help="trace capture window per rank")
    pr.set_defaults(fn=cmd_profile)

    k = sub.add_parser("kill", help="kill a running job (yarn "
                       "application -kill analogue)")
    k.add_argument("app_id", help="application id of a RUNNING job")
    k.add_argument("--workdir", help="client work dir (default ~/.tony-tpu/jobs)")
    k.add_argument("--reason", help="recorded in the job's final message")
    k.set_defaults(fn=cmd_kill)

    rz = sub.add_parser("resize", help="elastically resize a running "
                        "job's training gang (drain -> commit -> "
                        "re-gang -> restore)")
    rz.add_argument("num_workers", type=int,
                    help="target worker count after the resize")
    rz.add_argument("app_id", help="application id of a RUNNING job")
    rz.add_argument("--workdir", help="client work dir (default ~/.tony-tpu/jobs)")
    rz.set_defaults(fn=cmd_resize)

    lg = sub.add_parser("logs", help="print per-container logs "
                        "(yarn logs analogue, local substrate)")
    lg.add_argument("app_id", help="application id")
    lg.add_argument("--workdir", help="client work dir (default ~/.tony-tpu/jobs)")
    lg.add_argument("--tail", type=int, default=0,
                    help="only the last N lines of each log (0 = all)")
    lg.set_defaults(fn=cmd_logs)

    from tony_tpu.analysis.cli import CONFIG_NAMES  # jax-free constants

    an = sub.add_parser("analyze", help="run the jaxpr sharding/"
                        "collective invariant analyzer over the shipped "
                        "train-step configs")
    an.add_argument("--config", default="all",
                    choices=("all",) + CONFIG_NAMES,
                    help="which canonical config to analyze "
                         "(default: all)")
    an.add_argument("--json", help="also write the full structured "
                    "reports to this path")
    an.add_argument("--signatures", help="directory of committed step-"
                    "signature pins to check against "
                    "(e.g. tests/signatures)")
    an.add_argument("--update-signatures", action="store_true",
                    help="rewrite the signature pins instead of checking "
                         "(commit the diff)")
    an.add_argument("--lint", action="store_true",
                    help="also run the jnp.concatenate/stack pack-site "
                         "source lint (make lint)")
    an.add_argument("--concurrency", action="store_true",
                    help="run the host-side concurrency plane instead "
                         "of the jaxpr configs: lock-discipline lint, "
                         "lock-order deadlock check (static + witness), "
                         "thread-hygiene audit — jax-free")
    an.set_defaults(fn=cmd_analyze)

    v = sub.add_parser("version", help="print version")
    v.set_defaults(fn=cmd_version)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = make_parser().parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        # stdout piped into a pager/head that exited; not an error.
        return 0
