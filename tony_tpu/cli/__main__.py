"""``python -m tony_tpu.cli`` — the ``tony`` entry point (reference:
``ClusterSubmitter.main`` via the ``tony-cli`` shadow jar)."""

import sys

from tony_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
